"""Tiered, mmap'd segment store: evictions become tiers, not garbage
(ISSUE 17 tentpole).

The serving plane materializes chunk bitsets (and chunk prime-value
arrays) on demand and caches them in :class:`~sieve.service.index.BitsetLRU`
— but before this module an eviction *discarded* the work and a restart
forgot everything. The store keeps every fact ever materialized in a
tiered, append-only, per-entry-checksummed file that N serving
processes on one host share read-only through the page cache:

  - **tier 0** — counts only (seeded from the checkpoint ledger):
    24 bytes of key + an 8-byte count, no payload
  - **tier 1** — tier 0 plus the 32-bit boundary words of the chunk's
    flag array (the cross-segment twin-splice currency)
  - **tier 2** — tier 1 plus the full prime set, wheel-compressed in
    value space at 48/210 residues (6 bytes per 210 integers — see
    :func:`sieve.bitset.pack_wheel210`); enough to rebuild the exact
    flag array for any layout without sieving

On-disk layout under ``<root>/``:

  - ``segstore.json`` — the generation pointer ``{gen, data}``, swapped
    atomically (tempfile + ``os.replace`` + dir fsync, the
    :mod:`sieve.checkpoint` durability idiom)
  - ``segstore_<gen>.dat`` — the append-only data file the pointer
    names: 48-byte record headers (magic, tier, key, count, boundary
    words, payload length, CRC32 over header+payload) + payload,
    8-byte aligned. Readers mmap it; a record is *immutable once
    appended*, so an entry survives any concurrent reader.
  - ``store.lock`` — ``flock`` serializing appends and the compaction
    swap across processes (every serving process may append demotions;
    only the elected writer compacts)

Crash/chaos honesty: a torn or garbled record fails its CRC and is
*skipped* — readers emit a counted ``store_torn_entry`` event, resync
on the record magic, and the chunk simply re-materializes later (the
``store_torn_write`` chaos kind injects exactly this). A truncated tail
(crash mid-append) reads as end-of-log; the writer trims it at open.

Generation follow: the background compactor rewrites live entries into
``segstore_<gen+1>.dat`` and atomically swaps the pointer; other
processes notice via the same ``(mtime_ns, size)`` fingerprint poll the
PR 8 ledger live-follow uses and rescan. Appends from any process are
picked up by size growth within a generation.

Everything here may block on file I/O **except** :meth:`stats` /
:meth:`health`, which read in-memory counters only so the event loop
can answer ``stats``/``health`` inline.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import mmap
import os
import struct
import tempfile
import threading
import time
import zlib

import numpy as np

from sieve import env
from sieve.analysis.lockdebug import named_lock
from sieve.bitset import (
    Layout,
    boundary_words,
    pack_wheel210,
    unpack_wheel210,
)
from sieve.checkpoint import ledger_fingerprint

try:
    import fcntl
except ImportError:  # non-posix: single-process best effort
    fcntl = None

# record header: magic u32 | tier u8 | small_mask u8 | pad u16 |
# lo u64 | hi u64 | count u64 | first_word u32 | last_word u32 |
# payload_len u32 | crc32 u32  == 48 bytes, followed by payload,
# zero-padded to 8-byte alignment. crc covers bytes [4:44) + payload.
_HEADER = struct.Struct("<IBB2xQQQIIII")
_HEADER_LEN = _HEADER.size
assert _HEADER_LEN == 48
_MAGIC = 0x53475631  # "SGV1" little-endian-ish tag
_ALIGN = 8

POINTER_NAME = "segstore.json"
LOCK_NAME = "store.lock"
_DATA_FMT = "segstore_%06d.dat"

TIER_COUNT = 0
TIER_BOUNDARY = 1
TIER_BITSET = 2


def _pad(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


@dataclasses.dataclass(frozen=True)
class StoreSettings:
    """Knobs for the tiered store, one env var each (all documented in
    README's "Tiered segment store" section)."""

    fsync: bool = False          # fsync every append (pointer swaps always)
    compact_s: float = 2.0       # compactor poll period; <= 0 disables
    compact_ratio: float = 0.5   # compact when dead/total exceeds this
    min_compact_bytes: int = 1 << 16  # ... and dead bytes exceed this
    t2_bytes: int = 0            # tier-2 payload cap; 0 = uncapped
    refresh_s: float = 0.25      # reader min interval between stat polls

    @classmethod
    def from_env(cls) -> "StoreSettings":
        return cls(
            fsync=env.env_flag("SIEVE_STORE_FSYNC", False),
            compact_s=env.env_float("SIEVE_STORE_COMPACT_S", 2.0),
            compact_ratio=env.env_float("SIEVE_STORE_COMPACT_RATIO", 0.5),
            min_compact_bytes=env.env_int(
                "SIEVE_STORE_MIN_COMPACT_BYTES", 1 << 16),
            t2_bytes=env.env_int("SIEVE_STORE_T2_BYTES", 0),
            refresh_s=env.env_float("SIEVE_STORE_REFRESH_S", 0.25),
        )


@dataclasses.dataclass
class _Entry:
    tier: int
    count: int
    first_word: int
    last_word: int
    small_mask: int
    rec_off: int       # offset of the record header in the data file
    rec_len: int       # padded record length
    payload_len: int


class TieredSegmentStore:
    """One directory of tiered segment facts, shared by N processes.

    ``writer=True`` marks the elected writer (proc 0 of a ``--procs``
    fleet, or the only process): it trims torn tails at open, imports
    ledger counts, and owns the background compactor. *Every* process —
    writer or reader — may append demotions; appends are serialized by
    the cross-process ``flock``.
    """

    def __init__(
        self,
        root: str | os.PathLike,
        *,
        writer: bool = False,
        settings: StoreSettings | None = None,
        chaos=None,
        events=None,
    ) -> None:
        self.root = os.fspath(root)
        self.writer = writer
        self.settings = settings or StoreSettings()
        self._chaos = chaos  # guard: none(ChaosSchedule is internally locked)
        self._events = events  # guard: none(set once at construction)
        os.makedirs(self.root, exist_ok=True)

        # one lock for all mutable store state; never held across the
        # events callback's metrics sinks is fine (leaf locks are
        # inside it in the canonical order), but never nests under
        # BitsetLRU._lock — demotion callbacks fire outside the LRU lock
        self._lock = named_lock("TieredSegmentStore._lock")
        self._entries: dict[tuple[int, int], _Entry] = {}  # guard: _lock
        self._gen = 0              # guard: _lock — generation pointer
        self._pointer_fp = None    # guard: _lock — pointer fingerprint
        self._data_path = ""       # guard: _lock
        self._data_fd = -1         # guard: _lock
        self._append_fd = -1       # guard: _lock
        self._mmap: mmap.mmap | None = None  # guard: _lock
        self._scan_off = 0         # guard: _lock — bytes parsed so far
        self._data_size = 0        # guard: _lock — bytes known on disk
        self._dead_bytes = 0       # guard: _lock — superseded/torn bytes
        self._t2_payload = 0       # guard: _lock — live tier-2 payload bytes
        self._last_refresh = 0.0   # guard: _lock
        self._writes = 0           # guard: _lock — chaos draw counter
        # counters surfaced by stats()/health() (in-memory only)
        self._hits = 0             # guard: _lock
        self._misses = 0           # guard: _lock
        self._demotions = 0        # guard: _lock
        self._demoted_bytes = 0    # guard: _lock
        self._torn = 0             # guard: _lock
        self._torn_writes = 0      # guard: _lock
        self._compactions = 0      # guard: _lock
        self._compact_errors = 0   # guard: _lock
        self._downgraded = 0       # guard: _lock

        lock_path = os.path.join(self.root, LOCK_NAME)
        self._lock_fd = os.open(lock_path, os.O_RDWR | os.O_CREAT, 0o644)  # guard: none(set in __init__, cleared once in close() after the compactor is joined)
        self._stop = threading.Event()
        self._compactor: threading.Thread | None = None  # guard: none(set
        # once in start() before the thread exists, joined in close())

        with self._lock:
            with self._flock():
                self._open_gen_locked(create=True)
                if self.writer:
                    self._trim_torn_tail_locked()
            self._scan_locked()

    # --- cross-process serialization ------------------------------------------

    @contextlib.contextmanager
    def _flock(self):
        if fcntl is None:
            yield
            return
        fcntl.flock(self._lock_fd, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(self._lock_fd, fcntl.LOCK_UN)

    # --- generation pointer ----------------------------------------------------

    @property
    def _pointer_path(self) -> str:
        return os.path.join(self.root, POINTER_NAME)

    def _write_pointer_locked(self, gen: int, data_name: str) -> None:  # holds: _lock
        """Atomic pointer swap, sieve.checkpoint durability idiom."""
        doc = {"version": 1, "gen": gen, "data": data_name}
        fd, tmp = tempfile.mkstemp(
            prefix=".segstore.", suffix=".tmp", dir=self.root)
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._pointer_path)
        except BaseException:
            with contextlib.suppress(OSError):
                os.unlink(tmp)
            raise
        dfd = os.open(self.root, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

    def _open_gen_locked(self, create: bool = False) -> None:  # holds: _lock
        """(Re)open the data file the pointer names; resets the parse
        state — callers rescan."""
        ptr = self._pointer_path
        if not os.path.exists(ptr):
            if not create:
                raise FileNotFoundError(ptr)
            data_name = _DATA_FMT % 1
            with open(os.path.join(self.root, data_name), "ab"):
                pass
            self._write_pointer_locked(1, data_name)
        with open(ptr, encoding="utf-8") as f:
            doc = json.load(f)
        self._close_files_locked()
        self._gen = int(doc["gen"])
        self._data_path = os.path.join(self.root, str(doc["data"]))
        self._pointer_fp = ledger_fingerprint(ptr)
        self._data_fd = os.open(self._data_path, os.O_RDONLY)
        self._append_fd = os.open(
            self._data_path, os.O_WRONLY | os.O_APPEND)
        self._entries.clear()
        self._scan_off = 0
        self._data_size = 0
        self._dead_bytes = 0
        self._t2_payload = 0
        self._remap_locked()

    def _close_files_locked(self) -> None:  # holds: _lock
        if self._mmap is not None:
            with contextlib.suppress(BufferError):
                self._mmap.close()
            self._mmap = None
        for fd in (self._data_fd, self._append_fd):
            if fd >= 0:
                with contextlib.suppress(OSError):
                    os.close(fd)
        self._data_fd = self._append_fd = -1

    def _remap_locked(self) -> None:  # holds: _lock
        size = os.fstat(self._data_fd).st_size
        self._data_size = size
        if self._mmap is not None:
            with contextlib.suppress(BufferError):
                self._mmap.close()
            self._mmap = None
        if size:
            self._mmap = mmap.mmap(
                self._data_fd, size, access=mmap.ACCESS_READ)

    def _check_gen_locked(self) -> bool:  # holds: _lock
        """Follow a pointer swap (compaction in another process).
        Returns True when the generation changed (state was reset)."""
        fp = ledger_fingerprint(self._pointer_path)
        if fp == self._pointer_fp:
            return False
        self._open_gen_locked()
        return True

    # --- record scan -----------------------------------------------------------

    def _trim_torn_tail_locked(self) -> None:  # holds: _lock
        """Writer, at open, under flock: drop a crash-truncated tail so
        later appends start on a record boundary."""
        size = os.fstat(self._data_fd).st_size
        end = self._scan_extent_locked(size)
        if end < size:
            os.ftruncate(self._append_fd, end)

    def _scan_extent_locked(self, size: int) -> int:  # holds: _lock
        """Last byte offset that ends a structurally complete record."""
        self._remap_locked()
        off = 0
        mm = self._mmap
        while mm is not None and off + _HEADER_LEN <= size:
            magic, _t, _m, _lo, _hi, _c, _fw, _lw, plen, _crc = \
                _HEADER.unpack_from(mm, off)
            total = _pad(_HEADER_LEN + plen)
            if magic != _MAGIC or off + total > size:
                break
            off += total
        return off

    def _scan_locked(self) -> None:  # holds: _lock
        """Parse records from ``_scan_off`` to EOF, indexing entries and
        skipping torn ones (CRC failure -> ``store_torn_entry``)."""
        size = os.fstat(self._data_fd).st_size
        if size <= self._scan_off:
            return
        self._remap_locked()
        mm = self._mmap
        off = self._scan_off
        torn_events = []
        while mm is not None and off + _HEADER_LEN <= size:
            (magic, tier, small_mask, lo, hi, count, fw, lw, plen,
             crc) = _HEADER.unpack_from(mm, off)
            total = _pad(_HEADER_LEN + plen)
            if magic != _MAGIC or hi <= lo or off + total > size:
                if magic == _MAGIC and off + total > size:
                    break  # partial tail: wait for the rest
                # garbage header: resync on the next aligned magic
                torn_events.append(off)
                self._torn += 1
                nxt = off + _ALIGN
                while nxt + _HEADER_LEN <= size:
                    if _HEADER.unpack_from(mm, nxt)[0] == _MAGIC:
                        break
                    nxt += _ALIGN
                self._dead_bytes += nxt - off
                off = nxt
                continue
            payload = mm[off + _HEADER_LEN:off + _HEADER_LEN + plen]
            if zlib.crc32(mm[off + 4:off + 44] + payload) != crc:
                torn_events.append(off)
                self._torn += 1
                self._dead_bytes += total
                off += total
                continue
            self._index_locked(
                (lo, hi),
                _Entry(tier, count, fw, lw, small_mask, off, total, plen),
            )
            off += total
        self._scan_off = off
        for toff in torn_events:
            self._emit("store_torn_entry", quietable=True,
                       offset=toff, gen=self._gen)

    def _index_locked(self, key: tuple[int, int], entry: _Entry) -> None:  # holds: _lock
        old = self._entries.get(key)
        if old is not None:
            if old.tier > entry.tier:
                # never let a late low-tier append shadow richer data
                self._dead_bytes += entry.rec_len
                return
            self._dead_bytes += old.rec_len
            if old.tier == TIER_BITSET:
                self._t2_payload -= old.payload_len
        self._entries[key] = entry
        if entry.tier == TIER_BITSET:
            self._t2_payload += entry.payload_len

    # --- appends ---------------------------------------------------------------

    def _build_record(self, tier: int, lo: int, hi: int, count: int,
                      fw: int, lw: int, small_mask: int,
                      payload: bytes) -> bytes:
        hdr = _HEADER.pack(_MAGIC, tier, small_mask, lo, hi, count,
                           fw, lw, len(payload), 0)
        crc = zlib.crc32(hdr[4:44] + payload)
        hdr = _HEADER.pack(_MAGIC, tier, small_mask, lo, hi, count,
                           fw, lw, len(payload), crc)
        rec = hdr + payload
        return rec + b"\0" * (_pad(len(rec)) - len(rec))

    def _append_locked(self, key, tier, count,  # holds: _lock
                       fw: int, lw: int, small_mask: int,
                       payload: bytes) -> bool:
        """Append one record under the cross-process flock. Returns
        False when the record was deliberately torn by chaos."""
        rec = self._build_record(
            tier, key[0], key[1], count, fw, lw, small_mask, payload)
        self._writes += 1
        torn = bool(self._chaos is not None and self._chaos.take_kinds(
            0, self._writes, ("store_torn_write",)))
        if torn:
            # same length, garbled interior: the CRC fails but the
            # framing survives, so readers skip exactly this record.
            # [8:40) garbles lo/hi/count/first/last but leaves magic
            # and payload_len intact — torn records must never confuse
            # the scanner about where the NEXT record starts.
            body = bytearray(rec)
            for i in range(8, 40):
                body[i] ^= 0xA5
            rec = bytes(body)
        with self._flock():
            # a compaction may have swapped generations since our last
            # look — re-anchor before appending so nothing lands in a
            # dead file
            self._check_gen_locked()
            off = os.lseek(self._append_fd, 0, os.SEEK_END)
            os.write(self._append_fd, rec)
            if self.settings.fsync:
                os.fsync(self._append_fd)
        self._data_size = off + len(rec)
        if torn:
            self._torn_writes += 1
            self._torn += 1
            self._dead_bytes += len(rec)
            self._scan_off = max(self._scan_off, off + len(rec))
            self._emit("store_torn_entry", quietable=True,
                       offset=off, gen=self._gen)
            return False
        if self._scan_off == off:
            self._scan_off = off + len(rec)
            self._index_locked(key, _Entry(
                tier, count, fw, lw, small_mask, off, len(rec),
                len(payload)))
        # else: another process appended in between; the next scan
        # picks both records up in order
        return True

    # --- public write API ------------------------------------------------------

    def put_count(self, lo: int, hi: int, count: int) -> None:
        """Tier-0 fact (ledger import / count-only demotion)."""
        with self._lock:
            if (lo, hi) in self._entries:
                return
            self._append_locked((lo, hi), TIER_COUNT, count, 0, 0, 0, b"")

    def put_boundary(self, lo: int, hi: int, count: int,
                     first_word: int, last_word: int) -> bool:
        """Tier-1 fact (ISSUE 18): count plus the exact boundary flag
        words — what ``--persist-cold`` records per cold chunk so a
        restarted server can rebuild the chunk's full SegmentResult
        without re-marking it. Skipped when a boundary-or-richer entry
        already exists (never shadow richer data with a re-persist);
        returns False on a duplicate or a chaos-torn write."""
        with self._lock:
            cur = self._entries.get((lo, hi))
            if cur is not None and cur.tier >= TIER_BOUNDARY:
                return False
            return self._append_locked(
                (lo, hi), TIER_BOUNDARY, int(count),
                int(first_word), int(last_word), 0, b"")

    def put_flags(self, lo: int, hi: int, flags: np.ndarray,
                  layout: Layout) -> bool:
        """Demote a fully-sieved flag array into tier 2. The flag bits
        must be exact primality (post-sieve), not mid-sieve candidates —
        composite survivors off the 210-wheel cannot be encoded and
        raise in pack_wheel210. Returns False on a duplicate or a
        chaos-torn write."""
        values = layout.values_np(lo, np.flatnonzero(flags))
        fw, lw = boundary_words(flags)
        payload, small_mask = pack_wheel210(lo, hi, values)
        with self._lock:
            cur = self._entries.get((lo, hi))
            if cur is not None and cur.tier >= TIER_BITSET:
                return False  # already demoted (possibly by a peer)
            ok = self._append_locked(
                (lo, hi), TIER_BITSET, int(values.size), fw, lw,
                small_mask, payload)
            if ok:
                self._demotions += 1
                self._demoted_bytes += len(payload)
        if ok:
            self._emit("store_demoted", quietable=True, lo=lo, hi=hi,
                       bytes=len(payload), tier=TIER_BITSET)
        return ok

    def put_values(self, lo: int, hi: int, values: np.ndarray,
                   layout: Layout) -> bool:
        """Demote a prime-value array (the ``_pv`` cache) by rebuilding
        the layout flags so tier 1 boundary words stay truthful."""
        values = np.asarray(values, dtype=np.int64)
        nb = layout.nbits(lo, hi)
        flags = np.zeros(nb, dtype=bool)
        if nb and values.size:
            g0 = layout.gidx(layout.first_candidate(lo))
            flags[layout.gidx_np(values) - g0] = True
        return self.put_flags(lo, hi, flags, layout)

    def import_ledger(self, entries) -> int:
        """Seed tier 0 from ``(lo, hi, count)`` tuples (the checkpoint
        ledger's completed segments). Writer-only; idempotent."""
        added = 0
        with self._lock:
            for lo, hi, count in entries:
                if (lo, hi) in self._entries:
                    continue
                self._append_locked(
                    (int(lo), int(hi)), TIER_COUNT, int(count), 0, 0, 0, b"")
                added += 1
        return added

    # --- reads -----------------------------------------------------------------

    def _payload_locked(self, key, e) -> bytes | None:  # holds: _lock
        """Re-checksummed payload bytes for an indexed entry."""
        if self._mmap is None or e.rec_off + e.rec_len > len(self._mmap):
            self._remap_locked()
        mm = self._mmap
        if mm is None or e.rec_off + e.rec_len > len(mm):
            return None
        start = e.rec_off + _HEADER_LEN
        payload = mm[start:start + e.payload_len]
        crc = _HEADER.unpack_from(mm, e.rec_off)[9]
        if zlib.crc32(mm[e.rec_off + 4:e.rec_off + 44] + payload) != crc:
            # torn under us (disk corruption): behave like the scan —
            # skip, count, re-materialize upstream
            self._entries.pop(key, None)
            self._torn += 1
            self._dead_bytes += e.rec_len
            if e.tier == TIER_BITSET:
                self._t2_payload -= e.payload_len
            self._emit("store_torn_entry", quietable=True,
                       offset=e.rec_off, gen=self._gen)
            return None
        return payload

    def _maybe_refresh_locked(self, force: bool = False) -> bool:  # holds: _lock
        now = time.monotonic()
        if not force and now - self._last_refresh < self.settings.refresh_s:
            return False
        self._last_refresh = now
        changed = self._check_gen_locked()
        before = self._scan_off
        self._scan_locked()
        return changed or self._scan_off != before

    def maybe_refresh(self, force: bool = False) -> bool:
        """Follow peers: pointer swap (new generation) or same-gen
        append growth. Throttled by ``refresh_s`` unless forced."""
        with self._lock:
            return self._maybe_refresh_locked(force)

    def get_entry(self, lo: int, hi: int):
        """(tier, count, first_word, last_word) or None — no payload I/O."""
        with self._lock:
            e = self._entries.get((lo, hi))
            if e is None:
                return None
            return (e.tier, e.count, e.first_word, e.last_word)

    def load_values(self, lo: int, hi: int) -> np.ndarray | None:
        """Sorted prime values for a tier-2 entry, or None."""
        with self._lock:
            e = self._entries.get((lo, hi))
            if e is None or e.tier < TIER_BITSET:
                if self._maybe_refresh_locked():
                    e = self._entries.get((lo, hi))
            if e is None or e.tier < TIER_BITSET:
                self._misses += 1
                return None
            payload = self._payload_locked((lo, hi), e)
            if payload is None:
                self._misses += 1
                return None
            small_mask = e.small_mask
            self._hits += 1
        return unpack_wheel210(lo, hi, payload, small_mask)

    def load_flags(self, lo: int, hi: int,
                   layout: Layout) -> np.ndarray | None:
        """Rebuild the exact layout flag array for a tier-2 entry, or
        None (not stored / torn). The inverse of :meth:`put_flags`."""
        values = self.load_values(lo, hi)
        if values is None:
            return None
        nb = layout.nbits(lo, hi)
        flags = np.zeros(nb, dtype=bool)
        if nb and values.size:
            g0 = layout.gidx(layout.first_candidate(lo))
            pos = layout.gidx_np(values) - g0
            ok = (pos >= 0) & (pos < nb)
            # layout extras (2 for odds; 2,3,5 for wheel30) are not
            # candidates and were never stored from this layout, but a
            # foreign-packing value would alias a wrong bit — verify
            # the inverse map instead of trusting it
            ok &= layout.values_np(lo, np.clip(pos, 0, max(nb - 1, 0))) \
                == values
            flags[pos[ok]] = True
        return flags

    # --- compaction ------------------------------------------------------------

    def _needs_compact_locked(self) -> bool:  # holds: _lock
        s = self.settings
        if self._dead_bytes >= max(1, s.min_compact_bytes) and \
                self._dead_bytes > s.compact_ratio * max(1, self._data_size):
            return True
        return bool(s.t2_bytes and self._t2_payload > s.t2_bytes)

    def compact_once(self, force: bool = False) -> bool:
        """Rewrite live entries into ``segstore_<gen+1>.dat`` and swap
        the pointer atomically; under a tier-2 byte cap, the oldest
        tier-2 entries are downgraded to tier 1. Writer-only."""
        if not self.writer:
            return False
        with self._lock:
            with self._flock():
                self._check_gen_locked()
                self._scan_locked()
                if not force and not self._needs_compact_locked():
                    return False
                old_size, old_path = self._data_size, self._data_path
                gen = self._gen + 1
                data_name = _DATA_FMT % gen
                new_path = os.path.join(self.root, data_name)
                cap = self.settings.t2_bytes
                # oldest-first by record offset: append order is age
                items = sorted(
                    self._entries.items(), key=lambda kv: kv[1].rec_off)
                t2 = sum(e.payload_len for _, e in items
                         if e.tier == TIER_BITSET)
                downgraded = 0
                out: list[tuple[tuple[int, int], int, _Entry, bytes]] = []
                for key, e in items:
                    payload = b""
                    tier = e.tier
                    if e.tier == TIER_BITSET:
                        if cap and t2 > cap:
                            t2 -= e.payload_len
                            tier = TIER_BOUNDARY
                            downgraded += 1
                        else:
                            p = self._payload_locked(key, e)
                            if p is None:
                                continue  # torn: drop it entirely
                            payload = p
                    out.append((key, tier, e, payload))
                with open(new_path, "wb") as f:
                    off = 0
                    new_entries: dict[tuple[int, int], _Entry] = {}
                    for key, tier, e, payload in out:
                        rec = self._build_record(
                            tier, key[0], key[1], e.count, e.first_word,
                            e.last_word,
                            e.small_mask if tier == TIER_BITSET else 0,
                            payload)
                        f.write(rec)
                        new_entries[key] = _Entry(
                            tier, e.count, e.first_word, e.last_word,
                            e.small_mask if tier == TIER_BITSET else 0,
                            off, len(rec), len(payload))
                        off += len(rec)
                    f.flush()
                    os.fsync(f.fileno())
                self._write_pointer_locked(gen, data_name)
                self._close_files_locked()
                self._gen = gen
                self._data_path = new_path
                self._pointer_fp = ledger_fingerprint(self._pointer_path)
                self._data_fd = os.open(new_path, os.O_RDONLY)
                self._append_fd = os.open(
                    new_path, os.O_WRONLY | os.O_APPEND)
                self._entries = new_entries
                self._scan_off = off
                self._dead_bytes = 0
                self._t2_payload = sum(
                    e.payload_len for e in new_entries.values()
                    if e.tier == TIER_BITSET)
                self._remap_locked()
                with contextlib.suppress(OSError):
                    os.unlink(old_path)
                self._compactions += 1
                self._downgraded += downgraded
                live = len(new_entries)
                reclaimed = old_size - off
        self._emit("store_compacted", gen=gen, live=live,
                   reclaimed_bytes=reclaimed, downgraded=downgraded)
        return True

    def _compact_loop(self) -> None:
        while not self._stop.wait(self.settings.compact_s):
            try:
                self.compact_once()
            except Exception:
                with self._lock:
                    self._compact_errors += 1

    def start(self) -> None:
        """Spawn the background compactor (writer only; idempotent)."""
        if not self.writer or self.settings.compact_s <= 0:
            return
        if self._compactor is not None:
            return
        self._compactor = threading.Thread(
            target=self._compact_loop, name="store-compact", daemon=True)
        self._compactor.start()

    def close(self) -> None:
        self._stop.set()
        if self._compactor is not None:
            self._compactor.join(timeout=10.0)
            self._compactor = None
        with self._lock:
            self._close_files_locked()
        if self._lock_fd >= 0:
            with contextlib.suppress(OSError):
                os.close(self._lock_fd)
            self._lock_fd = -1

    def __enter__(self) -> "TieredSegmentStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --- observability (in-memory only: safe from the event loop) -------------

    def stats(self) -> dict:
        with self._lock:
            tiers = {0: 0, 1: 0, 2: 0}
            for e in self._entries.values():
                tiers[e.tier] += 1
            lookups = self._hits + self._misses
            return {
                "gen": self._gen,
                "writer": self.writer,
                "entries": dict(tiers),
                "data_bytes": self._data_size,
                "dead_bytes": self._dead_bytes,
                "tier2_payload_bytes": self._t2_payload,
                "hits": self._hits,
                "misses": self._misses,
                "hit_ratio": round(self._hits / lookups, 4) if lookups
                else None,
                "demotions": self._demotions,
                "demoted_bytes": self._demoted_bytes,
                "torn": self._torn,
                "torn_writes": self._torn_writes,
                "compactions": self._compactions,
                "compact_errors": self._compact_errors,
                "downgraded": self._downgraded,
                "appends": self._writes,
            }

    def health(self) -> dict:
        with self._lock:
            return {
                "gen": self._gen,
                "writer": self.writer,
                "entries": len(self._entries),
                "hits": self._hits,
                "demotions": self._demotions,
                "torn": self._torn,
            }

    def export_counts(self) -> list[tuple[int, int, int, int]]:
        """Sorted ``(lo, hi, count, tier)`` for every live entry — the
        export half of the ledger import/export seam."""
        with self._lock:
            return sorted(
                (lo, hi, e.count, e.tier)
                for (lo, hi), e in self._entries.items()
            )

    def _emit(self, kind: str, quietable: bool = False, **fields) -> None:
        if self._events is None:
            return
        try:
            self._events(kind, quietable=quietable, **fields)
        except Exception:
            pass  # observability must never take the store down
