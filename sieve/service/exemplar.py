"""Tail-sampled exemplar retention (ISSUE 19).

Tracing before this PR was all-or-nothing: either ``--trace`` captures
every event (too heavy to leave on) or nothing is kept and a slow
request's spans are gone by the time anyone asks. Tail sampling flips
the decision to request *completion*, when the outcome is known: the
tracer's always-on exemplar ring (``sieve/trace.py``) holds the recent
ctx-carrying spans cheaply, and this module's :class:`ExemplarSampler`
decides which requests' span trees are worth keeping —

* every request that ended typed-error / shed / degraded / demoted
  (the ``reason="error"`` / ``"flagged"`` rules — 100% retention, the
  acceptance bar),
* any request whose latency exceeded the sampler's own rolling p95
  times ``exemplar_slack`` (armed only after ``exemplar_warmup``
  observations — a cold window has no percentile), and
* a deterministic 1-in-``exemplar_baseline`` healthy baseline, so a
  report always has normal requests to diff the outliers against.

Kept exemplars are JSON records ``{ts, role, ctx, op, outcome, ms,
reason, spans, ...}`` committed to a bounded in-memory ring (served
inline by the ``exemplars`` wire op — the router pulls shard-side
exemplars so a slow route and its downstream query land in one file)
and, when a ``debug_dir`` is set, appended to a size-capped rolling
``exemplars.jsonl`` (at the cap the file rotates to ``.1``; one
generation of history survives). Render with::

    python tools/trace_report.py <debug_dir>/exemplars.jsonl --exemplars

Both the service and the router embed one sampler (``role`` tells the
records apart in a merged file). Locking: ``_lock`` guards the decision
window and the kept ring (in-memory only — safe under the wire loop's
inline ``exemplars`` op); file appends are taken fully off the request
path — ``keep()`` only enqueues the record under ``_io_cond`` and a
lazy daemon writer thread drains the queue to disk, so a kept request
never pays the rotate+append (kept requests ARE the slow tail; a sync
write there lands exactly on the p95 the overhead gate measures).
``_io_cond`` is never held together with ``_lock``, and the writer
releases it before touching the file. ``flush()`` blocks until the
queue is drained — tests and shutdown call it before reading the file.
"""

from __future__ import annotations

import bisect
import collections
import json
import math
import os
import threading
import time
from typing import Any

from sieve.analysis.lockdebug import named_condition, named_lock

EXEMPLAR_FILE = "exemplars.jsonl"

# span ring armed on the process tracer when exemplar sampling is on:
# spans are collected at request completion (microseconds after they
# were recorded), so the ring only needs to cover the spans of the
# handful of requests in flight at once — 2048 is ~500 requests deep
EXEMPLAR_SPAN_RING = 2048


class ExemplarSampler:
    """Completion-time retention decider + kept-exemplar sink."""

    def __init__(
        self,
        role: str,
        *,
        slack: float = 2.0,
        baseline: int = 100,
        window: int = 256,
        warmup: int = 30,
        ring: int = 256,
        file_bytes: int = 4 << 20,
        debug_dir: str | None = None,
        logger: Any = None,
    ) -> None:
        self.role = role
        self._slack = float(slack)
        self._baseline = max(1, int(baseline))
        self._warmup = max(0, int(warmup))
        self._file_bytes = max(1, int(file_bytes))
        self._dir = debug_dir
        self._logger = logger
        self._lock = named_lock("ExemplarSampler._lock")
        self._io_cond = named_condition("ExemplarSampler._io_cond")
        self._window: collections.deque = collections.deque(
            maxlen=max(1, int(window))
        )  # guard: _lock — recent terminal latencies (ms), arrival order
        self._sorted: list = []  # guard: _lock — same values, kept sorted
        #                          (decide() runs per request; re-sorting
        #                          256 floats there is the p95 overhead)
        self._ring: collections.deque = collections.deque(
            maxlen=max(1, int(ring))
        )  # guard: _lock — kept exemplar records
        self._seen = 0      # guard: _lock
        self._kept = 0      # guard: _lock
        self._pending: list = []   # guard: _io_cond — records awaiting disk
        self._draining = False     # guard: _io_cond — writer mid-batch
        self._io_stop = False      # guard: _io_cond
        self._flush_req = False    # guard: _io_cond — skip the coalesce nap
        self._coalesce_s = 0.25    # guard: none(set once; writer-only read)
        self._writer: threading.Thread | None = None  # guard: _io_cond — lazy
        self._rotations = 0  # guard: none(written by the writer thread
        #                      only; stats() reads are advisory)

    # --- decision --------------------------------------------------------

    def decide(self, outcome: str, elapsed_ms: float,
               flagged: bool = False) -> str | None:
        """Retention reason for one completed request, or None to drop.

        ``flagged`` marks conditions the outcome string alone cannot
        carry (a demoted re-run, a degraded reply that still said ok).
        Only healthy latencies fold into the rolling window — an error
        storm (shed 0 ms replies, deadline blowups) must not move the
        slow-tail threshold — and the p95 is computed from observations
        *before* this one, so a request can never excuse itself."""
        with self._lock:
            self._seen += 1
            seen = self._seen
            ns = len(self._sorted)
            p95 = (self._sorted[max(0, math.ceil(0.95 * ns) - 1)]
                   if ns >= max(1, self._warmup) else None)
            if outcome == "ok":
                v = float(elapsed_ms)
                if len(self._window) == self._window.maxlen:
                    old = self._window.popleft()
                    del self._sorted[bisect.bisect_left(self._sorted, old)]
                self._window.append(v)
                bisect.insort(self._sorted, v)
        if outcome != "ok":
            return "error"
        if flagged:
            return "flagged"
        if p95 is not None:
            if elapsed_ms > p95 * self._slack:
                return "slow"
        # deterministic healthy baseline: request 1, 1+N, 1+2N, ... —
        # the very first request is always an exemplar
        if (seen - 1) % self._baseline == 0:
            return "baseline"
        return None

    # --- commit ----------------------------------------------------------

    def keep(self, record: dict) -> dict:
        """Commit one kept exemplar: stamp it, ring it, and hand it to
        the writer thread (rolling-file append + the
        ``service_exemplar_kept`` event). Returns the stamped record
        (callers embed it in tests/replies)."""
        rec = dict(record)
        rec["role"] = self.role
        rec.setdefault("ts", time.time())
        with self._lock:
            self._kept += 1
            self._ring.append(rec)
        # the file append AND the kept-event emit ride the writer
        # thread: keep() runs on the request path of exactly the slow
        # requests the overhead gate prices, so the only synchronous
        # work is the ring append above
        self._enqueue_file(rec)
        return rec

    def _enqueue_file(self, rec: dict) -> None:
        if self._dir is None and self._logger is None:
            return
        with self._io_cond:
            if self._io_stop:
                return
            if self._writer is None:
                self._writer = threading.Thread(
                    target=self._writer_loop,
                    name=f"exemplar-writer-{self.role}", daemon=True,
                )
                self._writer.start()
            self._pending.append(rec)
            if len(self._pending) == 1:
                # later keeps skip the notify: the writer is already
                # awake (napping on its coalesce deadline) and a wake
                # per keep is a context switch billed to the request
                self._io_cond.notify()

    def _writer_loop(self) -> None:
        while True:
            with self._io_cond:
                while not self._pending and not self._io_stop:
                    self._io_cond.wait()
                if not self._pending and self._io_stop:
                    return
                # coalesce: keeps arrive in bursts (an error storm, a
                # cold batch) — napping briefly turns N wake+write
                # cycles into one, keeping the writer's GIL/disk time
                # away from the requests being served right now; only
                # flush()/close() cut the nap short (keep() notifies
                # land as spurious wakes and loop back to the deadline)
                nap_until = time.monotonic() + self._coalesce_s
                while not (self._io_stop or self._flush_req):
                    left = nap_until - time.monotonic()
                    if left <= 0:
                        break
                    self._io_cond.wait(left)
                batch = self._pending
                self._pending = []
                self._draining = True
            # file I/O + event emit outside the condition: a slow disk
            # or console must never stall a keep() enqueue (only this
            # thread touches the file)
            for rec in batch:
                if self._dir is not None:
                    self._write_line(rec)
                if self._logger is not None:
                    self._logger.event(
                        "service_exemplar_kept", quietable=True,
                        role=self.role, ctx=rec.get("ctx"),
                        op=rec.get("op"), outcome=rec.get("outcome"),
                        reason=rec.get("reason"), ms=rec.get("ms"),
                        spans=len(rec.get("spans") or ()),
                    )
            with self._io_cond:
                self._draining = False
                if not self._pending:
                    self._flush_req = False
                self._io_cond.notify_all()

    def _write_line(self, rec: dict) -> None:
        path = os.path.join(self._dir, EXEMPLAR_FILE)
        line = json.dumps(rec) + "\n"
        try:
            os.makedirs(self._dir, exist_ok=True)
            # rotate BEFORE appending: the live file stays under the
            # cap and a kept exemplar is never split across files
            try:
                if os.path.getsize(path) + len(line) > self._file_bytes:
                    os.replace(path, path + ".1")
                    self._rotations += 1
            except OSError:
                pass  # no file yet
            with open(path, "a", encoding="utf-8") as f:
                f.write(line)
        except OSError:
            # a full/readonly disk must never fail the request that
            # was merely being sampled; the in-memory ring still has
            # the exemplar for the wire op
            pass

    def flush(self, timeout_s: float = 5.0) -> None:
        """Block until every enqueued exemplar has reached the file (or
        the timeout lapses). Readers of ``exemplars.jsonl`` in the same
        process — tests, shutdown — call this first."""
        if self._dir is None and self._logger is None:
            return
        deadline = time.monotonic() + timeout_s
        with self._io_cond:
            if self._pending or self._draining:
                self._flush_req = True
                self._io_cond.notify_all()
            while self._pending or self._draining:
                left = deadline - time.monotonic()
                if left <= 0:
                    return
                self._io_cond.wait(left)

    def close(self) -> None:
        """Drain the queue and retire the writer thread. Idempotent;
        keeps after close still land in the in-memory ring but are no
        longer written to disk."""
        with self._io_cond:
            self._io_stop = True
            self._io_cond.notify_all()
            writer = self._writer
        if writer is not None:
            writer.join(timeout=5)

    # --- reads -----------------------------------------------------------

    def tail(self, n: int | None = None,
             ctx_prefix: str | None = None) -> list[dict]:
        """Newest kept exemplars (all when ``n`` is None), optionally
        filtered by ``ctx`` prefix. In-memory only: safe inline on the
        wire event loop."""
        with self._lock:
            recs = list(self._ring)
        if ctx_prefix:
            recs = [r for r in recs
                    if str(r.get("ctx", "")).startswith(ctx_prefix)]
        if n is not None and n >= 0:
            recs = recs[-n:]
        return recs

    def stats(self) -> dict:
        with self._lock:
            return {"seen": self._seen, "kept": self._kept,
                    "ring": len(self._ring)}


def load_exemplars(path: str) -> list[dict]:
    """Parse an ``exemplars.jsonl`` (or its ``.1`` rotation), skipping a
    torn tail line — the file is appended live and a reader must never
    crash on the record being written."""
    out: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail (or foreign junk): skip, keep going
            if isinstance(rec, dict):
                out.append(rec)
    return out


__all__ = [
    "EXEMPLAR_FILE",
    "EXEMPLAR_SPAN_RING",
    "ExemplarSampler",
    "load_exemplars",
]
