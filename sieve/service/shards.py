"""Shard map: contiguous range partition of the keyspace (ISSUE 11).

A :class:`ShardMap` describes how [2, N) is split into contiguous,
non-overlapping range shards, each served by its own ledger-backed
replica set. The router (sieve/service/router.py) is a pure function of
this map: every routing decision — which shard owns a point, which
shards a window intersects, where pair counts must be spliced — is
derived here, so the map is validated once at construction and the
router never has to re-check geometry per request.

Two wire-ins exist, both producing the same validated object:

* a JSON file (``--shard-map map.json``)::

      {"shards": [{"lo": 2, "hi": 500000, "addrs": ["127.0.0.1:7701"]},
                  {"lo": 500000, "hi": 1000001,
                   "addrs": ["127.0.0.1:7711", "127.0.0.1:7712"]}]}

* repeated CLI flags (``--shard 2:500000=127.0.0.1:7701``).

Validation is by-name so misconfigurations are diagnosable from the
error string alone: ``unsorted`` (shards not in ascending order),
``overlap`` (a shard starts before its predecessor ends), ``gap`` (a
shard starts after its predecessor ends). The last shard is special:
queries beyond ``map.hi`` route to it, because its server's cold tier
is what grows the fabric's covered range.
"""

from __future__ import annotations

import bisect
import dataclasses
import json
from typing import Iterable, Sequence

# A shard narrower than this could let one pair (max gap 4) straddle two
# shard edges at once, which the single-edge splice does not handle; no
# real deployment shards the number line this finely.
MIN_SPAN = 16


def _num(text: str) -> int:
    """Parse a shard bound: plain int, 1e6 style, or 10**6 style."""
    s = text.strip().replace("_", "")
    try:
        if "**" in s:
            base, exp = s.split("**", 1)
            return int(base) ** int(exp)
        if "e" in s.lower():
            f = float(s)
            if f != int(f):
                raise ValueError
            return int(f)
        return int(s)
    except (ValueError, TypeError):
        raise ValueError(f"bad shard bound: {text!r}") from None


@dataclasses.dataclass(frozen=True)
class Shard:
    """One contiguous range [lo, hi) and the replica addresses serving it."""

    lo: int
    hi: int
    addrs: tuple[str, ...]

    def __post_init__(self) -> None:
        if not isinstance(self.lo, int) or not isinstance(self.hi, int):
            raise ValueError("shard bounds must be integers")
        if self.lo < 2:
            raise ValueError(f"shard lo must be >= 2, got {self.lo}")
        if self.hi <= self.lo:
            raise ValueError(f"shard range empty: [{self.lo}, {self.hi})")
        if self.hi - self.lo < MIN_SPAN:
            raise ValueError(
                f"shard [{self.lo}, {self.hi}) narrower than MIN_SPAN="
                f"{MIN_SPAN}: pair splice assumes one edge per pair")
        if not self.addrs:
            raise ValueError(f"shard [{self.lo}, {self.hi}) has no addrs")
        object.__setattr__(self, "addrs", tuple(str(a) for a in self.addrs))

    def to_dict(self) -> dict:
        return {"lo": self.lo, "hi": self.hi, "addrs": list(self.addrs)}


class ShardMap:
    """Validated, ordered partition of [lo, hi) into contiguous shards."""

    def __init__(self, shards: Sequence[Shard]):
        shards = list(shards)
        if not shards:
            raise ValueError("shard map is empty")
        for prev, cur in zip(shards, shards[1:]):
            if cur.lo < prev.lo:
                raise ValueError(
                    f"unsorted shard map: [{cur.lo}, {cur.hi}) listed after "
                    f"[{prev.lo}, {prev.hi})")
            if cur.lo < prev.hi:
                raise ValueError(
                    f"overlap in shard map: [{cur.lo}, {cur.hi}) begins "
                    f"inside [{prev.lo}, {prev.hi})")
            if cur.lo > prev.hi:
                raise ValueError(
                    f"gap in shard map: [{prev.hi}, {cur.lo}) is covered by "
                    f"no shard")
        self.shards: tuple[Shard, ...] = tuple(shards)
        self._los = [s.lo for s in self.shards]

    @property
    def lo(self) -> int:
        return self.shards[0].lo

    @property
    def hi(self) -> int:
        return self.shards[-1].hi

    def __len__(self) -> int:
        return len(self.shards)

    def __iter__(self):
        return iter(self.shards)

    def shard_for(self, x: int) -> int:
        """Index of the shard owning value ``x``.

        Values at or beyond ``self.hi`` route to the last shard — its
        cold tier extends the fabric's range. Values below ``self.lo``
        are owned by nobody and raise.
        """
        if x < self.lo:
            raise ValueError(
                f"value {x} below shard map range [{self.lo}, {self.hi})")
        return min(bisect.bisect_right(self._los, x) - 1, len(self.shards) - 1)

    def shards_in(self, lo: int, hi: int) -> list[tuple[int, int, int]]:
        """Ascending (index, a, b) intersections of [lo, hi) with shards.

        The last shard's intersection extends to ``hi`` even past its
        declared ``hi`` (cold-tier extension). Empty for hi <= lo.
        """
        if hi <= lo:
            return []
        if lo < self.lo:
            raise ValueError(
                f"window [{lo}, {hi}) starts below shard map range "
                f"[{self.lo}, {self.hi})")
        parts: list[tuple[int, int, int]] = []
        first = self.shard_for(lo)
        for i in range(first, len(self.shards)):
            s = self.shards[i]
            a = max(lo, s.lo)
            b = hi if i == len(self.shards) - 1 else min(hi, s.hi)
            if b > a:
                parts.append((i, a, b))
            if b >= hi:
                break
        return parts

    def edges(self) -> list[int]:
        """Interior shard boundaries (where pair counts must be spliced)."""
        return [s.hi for s in self.shards[:-1]]

    def to_dict(self) -> dict:
        return {"shards": [s.to_dict() for s in self.shards]}

    @classmethod
    def from_dict(cls, data: dict) -> "ShardMap":
        if not isinstance(data, dict) or "shards" not in data:
            raise ValueError('shard map JSON must be {"shards": [...]}')
        shards = []
        for ent in data["shards"]:
            if not isinstance(ent, dict):
                raise ValueError(f"bad shard entry: {ent!r}")
            try:
                shards.append(Shard(int(ent["lo"]), int(ent["hi"]),
                                    tuple(ent["addrs"])))
            except (KeyError, TypeError) as e:
                raise ValueError(f"bad shard entry {ent!r}: {e}") from None
        return cls(shards)

    @classmethod
    def from_json(cls, path: str) -> "ShardMap":
        with open(path, encoding="utf-8") as f:
            return cls.from_dict(json.load(f))

    @classmethod
    def from_flags(cls, flags: Iterable[str]) -> "ShardMap":
        """Parse repeated ``--shard LO:HI=ADDR[,ADDR...]`` values."""
        shards = []
        for flag in flags:
            try:
                rng, addrs = flag.split("=", 1)
                lo_s, hi_s = rng.split(":", 1)
            except ValueError:
                raise ValueError(
                    f"bad --shard {flag!r}: expected LO:HI=ADDR[,ADDR...]"
                ) from None
            addr_list = tuple(a.strip() for a in addrs.split(",") if a.strip())
            shards.append(Shard(_num(lo_s), _num(hi_s), addr_list))
        return cls(shards)
