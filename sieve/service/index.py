"""Hot index tier: the checkpoint ledger as a queryable store.

A sieved checkpoint dir holds one :class:`SegmentResult` per completed
segment — per-segment prime counts keyed on segment boundaries. Sorted,
that is a prefix-sum index: ``pi(boundary)`` is O(log segments) with no
bitset touched. Values strictly inside a segment need flags for the
partial chunk only; those are materialized by the local numpy marking in
bounded chunks and kept in an LRU so a repeated hot query re-sieves
nothing (lru_hits vs materialized counters make that provable).

Only the *contiguous* prefix of segments starting at ``base`` (2 for a
whole-range server, the shard's lower bound for a range-sharded one,
ISSUE 11) is indexed: a partially-sieved ledger may have holes (cluster
runs complete segments out of order), and a prefix count across a hole
would be wrong. Ranges past :attr:`SieveIndex.covered_hi` are the
server's cold tier.

Per-query bookkeeping travels in a :class:`QueryCtx`: which tiers were
touched (drives the ``source`` field and the index-hit counter), the
prefix answered so far (drives typed ``deadline_exceeded`` partials),
and the deadline hook called before every chunk of real work.
"""

from __future__ import annotations

import bisect
import collections
import dataclasses
import math
import threading
from typing import Callable, Sequence

import numpy as np

from sieve import trace
from sieve.analysis.lockdebug import named_lock
from sieve.backends.cpu_numpy import sieve_segment_flags
from sieve.bitset import get_layout
from sieve.seed import seed_primes
from sieve.worker import SegmentResult

# Materialization chunk: matches enumerate._SLICE so one chunk is always
# a modest allocation no matter how large the ledger's segments are.
INDEX_CHUNK = 1 << 24


@dataclasses.dataclass
class QueryCtx:
    """Per-request bookkeeping threaded through index and cold tiers."""

    # deadline hook: called before each chunk of real work; raises
    # DeadlineExceeded (server-defined) reading answered_hi/count_so_far
    check: Callable[[], None] | None = None
    # tier provenance for the reply's "source" and the hit counters
    index: bool = False
    lru_hit: bool = False
    store_hit: bool = False
    materialized: bool = False
    cold: bool = False
    cold_cached: bool = False
    # progress for typed partial answers (prefix [2, answered_hi) done)
    answered_hi: int = 2
    count_so_far: int = 0
    # admission lane (ISSUE 10): "hot" requests demote to the cold lane
    # when they discover a chunk needing a backend dispatch; "cold" (the
    # default) never demotes, so contexts built outside the server's
    # admission path are unaffected
    lane: str = "cold"

    def tick(self) -> None:
        if self.check is not None:
            self.check()

    def source(self) -> str:
        hot = (self.index or self.lru_hit or self.store_hit
               or self.materialized or self.cold_cached)
        if self.cold:
            return "mixed" if hot else "cold"
        return "index" if hot else "none"


class BitsetLRU:
    """Bounded cache of materialized flag arrays keyed on (lo, hi).

    ``on_evict(lo, hi, arr)`` fires for every capacity eviction — the
    tiered segment store's demotion hook (ISSUE 17): work leaves the
    cache, not the process. It is invoked *outside* the cache lock (so
    the store's own lock never nests under it) and must not raise (the
    index wraps it with an error counter)."""

    def __init__(self, capacity: int, on_evict=None):
        self.capacity = capacity
        self.on_evict = on_evict  # guard: none(reference swap only; the
        # follower re-points it at each new index's demoter — any
        # snapshot's demoter writes identical bytes for a given key)
        self._lock = named_lock("BitsetLRU._lock")
        self._cache: "collections.OrderedDict[tuple[int, int], np.ndarray]" = (
            collections.OrderedDict()
        )

    def get(self, lo: int, hi: int) -> np.ndarray | None:
        with self._lock:
            flags = self._cache.get((lo, hi))
            if flags is not None:
                self._cache.move_to_end((lo, hi))
            return flags

    def put(self, lo: int, hi: int, flags: np.ndarray) -> None:
        flags.setflags(write=False)
        evicted = []
        with self._lock:
            self._cache[(lo, hi)] = flags
            self._cache.move_to_end((lo, hi))
            while len(self._cache) > self.capacity:
                evicted.append(self._cache.popitem(last=False))
        on_evict = self.on_evict
        if on_evict is not None:
            for (elo, ehi), arr in evicted:
                on_evict(elo, ehi, arr)

    def __len__(self) -> int:
        with self._lock:
            return len(self._cache)


class SieveIndex:
    """Sorted segment-boundary index over a read-only ledger snapshot."""

    def __init__(
        self,
        packing: str,
        entries: dict[int, SegmentResult] | Sequence[SegmentResult],
        lru_segments: int = 32,
        lru: BitsetLRU | None = None,
        base: int = 2,
        store=None,
    ):
        self.packing = packing
        self.layout = get_layout(packing)
        # range-sharded servers (ISSUE 11) anchor their contiguous prefix
        # at the shard's lower bound instead of 2; counts are then "primes
        # in [base, v)" and nth is "k-th prime >= base" — exactly the
        # shard-local semantics the router composes from cumulative totals
        self.base = max(2, int(base))
        segs = sorted(
            entries.values() if isinstance(entries, dict) else entries,
            key=lambda r: r.lo,
        )
        # contiguous prefix from base only — counts across a hole are wrong
        self.segments: list[SegmentResult] = []
        want_lo = self.base
        for r in segs:
            if r.lo != want_lo:
                break
            self.segments.append(r)
            want_lo = r.hi
        self.dropped_segments = len(segs) - len(self.segments)
        self._his = [r.hi for r in self.segments]
        self._prefix = np.cumsum(
            [r.count for r in self.segments], dtype=np.int64
        )
        # vectorized twins of _his / segment los for count_upto_batch:
        # one searchsorted row answers M boundaries at once (ISSUE 14)
        self._his_np = np.asarray(self._his, dtype=np.int64)
        self._los_np = np.asarray(
            [r.lo for r in self.segments], dtype=np.int64
        )
        self.covered_hi = self._his[-1] if self.segments else self.base
        self.total_primes = int(self._prefix[-1]) if self.segments else 0
        self.bounds: list[int] = [r.lo for r in self.segments] + (
            [self.covered_hi] if self.segments else []
        )
        # live-follow (ISSUE 8): a refreshed index is handed the previous
        # snapshot's LRU so hot queries stay hot across swaps — flags
        # content depends only on (packing, lo, hi), never on ledger
        # entries, so cached chunks are exact under any snapshot
        self.lru = lru if lru is not None else BitsetLRU(lru_segments)
        # chunk prime-value arrays for count_upto_batch (ISSUE 16): same
        # (lo, hi) keys as the flags LRU, content equally snapshot-free
        self._pv = BitsetLRU(lru_segments)
        # tiered segment store (ISSUE 17): consulted on LRU misses
        # before sieving, fed by LRU evictions. Shared across snapshot
        # swaps exactly like the LRU (content keys on (packing, lo, hi))
        self.store = store  # guard: none(reference set at construction;
        # the follower hands every new index the same store object)
        self._stat_lock = named_lock("SieveIndex._stat_lock")
        self.lru_hits = 0  # guard: _stat_lock
        self.materialized = 0  # guard: _stat_lock
        self.store_hits = 0  # guard: _stat_lock
        self.store_errors = 0  # guard: _stat_lock
        if store is not None:
            self.lru.on_evict = self._demote_flags
            self._pv.on_evict = self._demote_values

    # --- store demotion (ISSUE 17) ---------------------------------------

    def _demote_flags(self, lo: int, hi: int, flags: np.ndarray) -> None:
        """Eviction hook: a flag array leaves the LRU -> tier 2."""
        try:
            self.store.put_flags(lo, hi, flags, self.layout)
        except Exception:
            with self._stat_lock:
                self.store_errors += 1

    def _demote_values(self, lo: int, hi: int, values: np.ndarray) -> None:
        """Eviction hook for the prime-value cache (ISSUE 16's _pv)."""
        try:
            self.store.put_values(lo, hi, values, self.layout)
        except Exception:
            with self._stat_lock:
                self.store_errors += 1

    # --- flags -----------------------------------------------------------

    def get_flags(self, lo: int, hi: int, ctx: QueryCtx) -> np.ndarray:
        """Candidate flags for [lo, hi): LRU, else local sieve + cache.

        [lo, hi) must fit one materialization chunk; callers chunk via
        :meth:`chunks`. The deadline hook fires before a fresh sieve
        (cache hits are always allowed through — they are the point)."""
        flags = self.lru.get(lo, hi)
        if flags is not None:
            ctx.lru_hit = True
            with self._stat_lock:
                self.lru_hits += 1
            return flags
        if self.store is not None:
            flags = self.store.load_flags(lo, hi, self.layout)
            if flags is not None:
                ctx.store_hit = True
                with self._stat_lock:
                    self.store_hits += 1
                self.lru.put(lo, hi, flags)
                return flags
        ctx.tick()
        with trace.span("query.materialize", lo=lo, hi=hi):
            seeds = seed_primes(math.isqrt(hi - 1))
            flags = sieve_segment_flags(self.packing, lo, hi, seeds)
        ctx.materialized = True
        with self._stat_lock:
            self.materialized += 1
        self.lru.put(lo, hi, flags)
        return flags

    @staticmethod
    def chunks(lo: int, hi: int, chunk: int = INDEX_CHUNK):
        for clo in range(lo, hi, chunk):
            yield clo, min(clo + chunk, hi)

    def flags_for_slice(self, slo: int, shi: int, ctx: QueryCtx) -> np.ndarray | None:
        """enumerate.primes_in_range ``flags_fn``: serve a slice from the
        hot tier, or None when it lies past the covered range (the
        caller's cold tier takes over). Slices never straddle a segment
        boundary (the enumerate ``bounds`` contract), so a cached
        enclosing range can be bit-sliced exactly."""
        if shi > self.covered_hi or not self.segments:
            return None
        flags = self.lru.get(slo, shi)
        if flags is not None:
            ctx.lru_hit = True
            with self._stat_lock:
                self.lru_hits += 1
            return flags
        j = bisect.bisect_right(self.bounds, slo) - 1
        seg = self.segments[min(j, len(self.segments) - 1)]
        # materialize on the segment-aligned chunk grid count_upto uses:
        # one LRU/store key per chunk serves pi, count, and primes alike.
        # A per-query slice key would miss the tiered store (demotions
        # are chunk-keyed, ISSUE 17) and re-sieve ranges it already holds
        clo = seg.lo + (slo - seg.lo) // INDEX_CHUNK * INDEX_CHUNK
        chi = min(clo + INDEX_CHUNK, seg.hi)
        if shi <= chi:
            whole = self.get_flags(clo, chi, ctx)
            off = self.layout.nbits(clo, slo)
            return whole[off : off + self.layout.nbits(slo, shi)]
        if shi - slo > INDEX_CHUNK:
            return None  # oversized ask; let the caller sub-chunk
        return self.get_flags(slo, shi, ctx)  # chunk-straddling slice

    # --- prefix counts ---------------------------------------------------

    def count_upto(self, v: int, ctx: QueryCtx) -> int:
        """Primes in [base, v), for base <= v <= covered_hi.

        Boundary hits are pure O(log segments); interior values add a
        partial in-segment count over materialized chunks."""
        if v <= self.base:
            ctx.answered_hi = max(ctx.answered_hi, self.base)
            return 0
        if v > self.covered_hi:
            raise ValueError(
                f"count_upto({v}) beyond covered_hi={self.covered_hi}"
            )
        ctx.index = True
        j = bisect.bisect_right(self._his, v)
        base = int(self._prefix[j - 1]) if j else 0
        if j == len(self.segments) or v == self.segments[j].lo:
            ctx.answered_hi = max(ctx.answered_hi, v)
            ctx.count_so_far = max(ctx.count_so_far, base)
            return base
        seg = self.segments[j]
        ctx.count_so_far = max(ctx.count_so_far, base)
        # partial in-segment count: chunks are aligned from seg.lo so a
        # repeated hot query hits the same LRU keys. The final chunk is
        # materialized whole (up to the segment end, capped at one chunk)
        # and bit-sliced to v, again for key stability.
        total = base + self.layout.extras_in(seg.lo, v)
        for clo, chi in self.chunks(seg.lo, seg.hi):
            if clo >= v:
                break
            flags = self.get_flags(clo, chi, ctx)
            if chi > v:
                nb = self.layout.nbits(clo, v)
                total += int(np.count_nonzero(flags[:nb]))
            else:
                total += int(np.count_nonzero(flags))
            ctx.answered_hi = max(ctx.answered_hi, min(chi, v))
            ctx.count_so_far = max(ctx.count_so_far, total)
        return total

    def count_upto_batch(self, vs, ctx: QueryCtx) -> np.ndarray:
        """Prefix counts for MANY boundaries in one vectorized row
        (ISSUE 14 batch op): ``out[i]`` = primes in [base, vs[i]).

        One ``np.searchsorted`` over the segment boundaries plus one
        gather over ``_prefix`` answers every segment-boundary hit —
        the per-value bisect/branch cost of M scalar ``count_upto``
        calls collapses into two array ops. Values that land strictly
        inside a segment are grouped by segment and answered one chunk
        at a time with a single searchsorted against the chunk's cached
        prime values (:meth:`_count_interior`) — no per-value popcount
        walk. Same domain contract as ``count_upto``: every value in
        [base, covered_hi]."""
        arr = np.asarray(list(vs), dtype=np.int64)
        out = np.zeros(arr.size, dtype=np.int64)
        if arr.size == 0:
            return out
        if int(arr.min()) < self.base:
            raise ValueError(
                f"count_upto_batch: value below base={self.base}"
            )
        if int(arr.max()) > self.covered_hi:
            raise ValueError(
                f"count_upto_batch: value beyond covered_hi="
                f"{self.covered_hi}"
            )
        if not self.segments:
            return out  # empty index: every legal v equals base
        ctx.index = True
        nseg = len(self.segments)
        j = np.searchsorted(self._his_np, arr, side="right")
        bases = np.where(j > 0, self._prefix[np.maximum(j - 1, 0)], 0)
        # boundary hit: v == covered_hi (j == nseg) or v == segments[j].lo
        lo_j = np.where(j >= nseg, np.int64(self.covered_hi),
                        self._los_np[np.minimum(j, nseg - 1)])
        boundary = (j >= nseg) | (arr == lo_j)
        out[boundary] = bases[boundary]
        hi_seen = int(arr[boundary].max()) if bool(boundary.any()) else 0
        ctx.answered_hi = max(ctx.answered_hi, hi_seen, self.base)
        if bool(boundary.any()):
            ctx.count_so_far = max(ctx.count_so_far,
                                   int(out[boundary].max()))
        interior = np.nonzero(~boundary)[0]
        if interior.size:
            ji = j[interior]
            for sj in np.unique(ji):
                sel = interior[ji == sj]
                self._count_interior(int(sj), arr[sel], out, sel, ctx)
        return out

    def _count_interior(self, sj: int, varr: np.ndarray, out: np.ndarray,
                        sel: np.ndarray, ctx: QueryCtx) -> None:
        """Answer a batch of strictly-interior values of segment ``sj``
        in one vectorized row per chunk.

        The scalar fallback (one :meth:`count_upto` per value) repeats a
        full-chunk popcount walk for every value — the dominant cost of
        a hot batch (ISSUE 16). Instead the chunk's set bits are mapped
        to their candidate *values* once (:meth:`_chunk_primes`, LRU'd),
        and every value landing in the chunk is answered by a single
        ``np.searchsorted`` against that sorted array. Chunk keys stay
        aligned from seg.lo exactly as in the scalar path, so the two
        paths share LRU entries and deadline/demotion semantics (the
        tick still fires inside :meth:`get_flags` before a fresh sieve).
        """
        seg = self.segments[sj]
        base = int(self._prefix[sj - 1]) if sj else 0
        totals = np.full(varr.size, base, dtype=np.int64)
        for p in self.layout.extra_primes:  # extras_in(seg.lo, v), vectorized
            if p >= seg.lo:
                totals += varr > p
        ci = (varr - seg.lo) // INDEX_CHUNK
        vmax = int(varr.max())
        running = 0  # popcount of full chunks already walked
        for c, (clo, chi) in enumerate(self.chunks(seg.lo, seg.hi)):
            if clo >= vmax:
                break
            pv = self._chunk_primes(clo, chi, ctx)
            msk = ci == c
            if bool(msk.any()):
                totals[msk] += running + np.searchsorted(
                    pv, varr[msk], side="left"
                )
                out[sel[msk]] = totals[msk]
                ctx.answered_hi = max(ctx.answered_hi, int(varr[msk].max()))
                ctx.count_so_far = max(ctx.count_so_far,
                                       int(totals[msk].max()))
            if chi <= vmax:  # later chunk still holds values: roll prefix
                running += pv.size
                ctx.answered_hi = max(ctx.answered_hi, chi)
                ctx.count_so_far = max(
                    ctx.count_so_far,
                    base + self.layout.extras_in(seg.lo, chi) + running,
                )

    def _chunk_primes(self, clo: int, chi: int, ctx: QueryCtx) -> np.ndarray:
        """Sorted prime values in chunk [clo, chi) (layout extras excluded):
        the chunk's set bits mapped through ``values_np``. Cached in a
        second LRU so a hot batch costs one searchsorted, not a popcount
        walk; a hit here is an LRU hit for provenance purposes."""
        pv = self._pv.get(clo, chi)
        if pv is not None:
            ctx.lru_hit = True
            with self._stat_lock:
                self.lru_hits += 1
            return pv
        flags = self.get_flags(clo, chi, ctx)
        pv = self.layout.values_np(clo, np.flatnonzero(flags))
        self._pv.put(clo, chi, pv)
        return pv

    # --- selection -------------------------------------------------------

    def nth(self, k: int, ctx: QueryCtx) -> int:
        """Value of the k-th prime (1-indexed), for 1 <= k <= total_primes."""
        if not 1 <= k <= self.total_primes:
            raise ValueError(f"nth({k}) outside indexed range")
        ctx.index = True
        j = int(np.searchsorted(self._prefix, k, side="left"))
        base = int(self._prefix[j - 1]) if j else 0
        seg = self.segments[j]
        r = k - base  # r-th prime within segment j
        # layout extras (2/3/5) always precede every candidate (>= 7 for
        # wheel30, >= 3 for odds) in any segment that contains them
        extras = [p for p in self.layout.extra_primes if seg.lo <= p < seg.hi]
        if r <= len(extras):
            return extras[r - 1]
        r -= len(extras)
        ctx.count_so_far = max(ctx.count_so_far, base + len(extras))
        for clo, chi in self.chunks(seg.lo, seg.hi):
            flags = self.get_flags(clo, chi, ctx)
            c = int(np.count_nonzero(flags))
            if r <= c:
                pos = np.nonzero(flags)[0][r - 1]
                return int(self.layout.values_np(clo, np.array([pos]))[0])
            r -= c
            ctx.count_so_far += c
            ctx.answered_hi = max(ctx.answered_hi, chi)
        raise AssertionError(
            f"segment {seg.seg_id} count={seg.count} disagrees with its "
            f"materialized flags — ledger/compute mismatch"
        )

    def stats(self) -> dict:
        with self._stat_lock:
            return {
                "segments": len(self.segments),
                "dropped_segments": self.dropped_segments,
                "covered_hi": self.covered_hi,
                "total_primes": self.total_primes,
                "lru_hits": self.lru_hits,
                "materialized": self.materialized,
                "store_hits": self.store_hits,
                "store_errors": self.store_errors,
                "lru_entries": len(self.lru),
            }
