"""The query server: bounded admission, deadlines, coalescing, degradation.

One :class:`SieveService` owns four tiers:

* **index** — :class:`~sieve.service.index.SieveIndex` over a
  ``Ledger.open_readonly`` snapshot; O(log segments) prefix counts plus
  an LRU of materialized bitsets. Hot queries never touch a backend.
* **admission** — a bounded queue in front of a small worker pool. A
  full queue (or an injected ``svc_shed``) returns a typed
  ``overloaded`` reply immediately — a request is never silently
  parked. Every admitted request carries a deadline; blowing it returns
  a typed ``deadline_exceeded`` with the partial prefix answered so far.
* **cold** — ranges past the index fall through to a real backend via
  the :class:`~sieve.worker.SieveWorker` seam, chunked on a fixed grid
  so concurrent overlapping queries coalesce: one leader computes a
  chunk, followers wait on its flight and share the result, and the
  result is cached so a repeated cold query becomes hot. The admission
  queue is the batching point (ISSUE 9): a :class:`ColdBatcher` thread
  drains every distinct chunk registered by queued requests and issues
  ONE backend dispatch for the whole sorted list through the
  ``SieveWorker.process_segments`` seam — on the jax backend the
  chunks stack into a single vmapped device launch, so M overlapping
  cold queries cost at most distinct-chunk dispatches, not M round
  trips. A chaos-failed chunk (``svc_batch_partial``) degrades only
  its own waiters; surviving chunks in the same batch answer exact.
* **degradation** — a circuit breaker around the backend: a failure
  streak (or an injected ``backend_down``) opens it for a cooldown,
  cold queries fail fast with a typed ``degraded`` reply, and the
  server keeps answering hot-index queries while reporting degraded
  health. It never trades exactness for availability — a reply is
  exact or it is a typed error.

Replication (ISSUE 8) adds two lifecycle behaviors on top:

* **live follow** — a :class:`LedgerFollower` polls the ledger file
  (fingerprint stat every ``SIEVE_SVC_REFRESH_S``) and, when the
  writing coordinator has extended it, re-opens read-only and swaps in
  a fresh :class:`SieveIndex` *by one reference assignment* — in-flight
  queries finish on the snapshot they started on, the new index
  inherits the old BitsetLRU so hot queries stay hot, and
  ``covered_hi`` is monotonic per process (a regressing or corrupt or
  mid-quarantine read is a *skipped* refresh with a
  ``service_refresh_failed`` event, never a crash and never a shrink).
* **cold write-back** (ISSUE 9) — with ``--persist-cold`` this server
  is the designated *writer* for its checkpoint dir: every batch of
  cold chunk results is recorded into the ledger via one checksummed
  atomic fsync'd flush (``Ledger.record_many``), keyed
  ``COLD_SEG_BASE + lo``. The ledger's ``covered_hi`` therefore grows
  under read traffic; the server's own follower (and every replica
  following the same file) swaps the extended coverage in through the
  ordinary refresh path, so a restart — or a peer — answers yesterday's
  cold ranges from the index.
* **graceful drain** — SIGTERM or a ``shutdown`` control message flips
  the server to draining: the listener closes, queued work is answered
  to completion, new queries are shed as typed ``draining``, and
  :meth:`SieveService.wait_drained` releases the host process once the
  last in-flight reply is out (the CLI exits 0 after at most
  ``SIEVE_SVC_DRAIN_S``). A rolling restart loses zero in-flight
  answers.

Wire protocol (sieve/rpc.py framing; one JSON object per message):

    {"type": "query", "id": i, "op": "pi", "x": 10**9, "deadline_s": 2}
    {"type": "reply", "id": i, "ok": true, "op": "pi", "value": 50847534,
     "source": "index", "elapsed_ms": 0.4}
    {"type": "reply", "id": i, "ok": false, "error": "deadline_exceeded",
     "detail": "...", "partial": {"answered_hi": ..., "pi_so_far": ...}}

Multiplexed wire plane (ISSUE 14): the listener is a single-threaded
``selectors`` event loop, not a thread-per-connection reader. Reads are
non-blocking and stream through an incremental
:class:`~sieve.rpc.FrameDecoder`, so a client may pipeline any number
of requests on one connection; replies correlate by ``id`` and come
back in COMPLETION order, not submission order. Each connection owns a
bounded write queue (``SIEVE_SVC_WRITE_QUEUE`` bytes; overflow closes
the connection as a slow consumer with a ``service_slow_consumer``
event) and ``health`` / ``stats`` / ``metrics`` / ``debug`` / ``chaos``
replies are front-inserted ahead of queued query replies — health stays
observable even when the worker pool is wedged. One dribbling
connection (the ``svc_slow_frame`` chaos kind throttles its write-side
to N bytes per tick) cannot head-of-line block any other connection.

The ``batch`` query op carries M members
(``{"op": "pi"|"is_prime"|"count", ...}``) in one frame; every hot
member resolves through ONE vectorized searchsorted row
(:meth:`SieveIndex.count_upto_batch`), cold members walk the
ColdBatcher individually, and each member gets its own typed outcome —
one member's shed/deadline never poisons its neighbors.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import os
import queue
import selectors
import socket
import threading
import time
from typing import TYPE_CHECKING, Any

import numpy as np

from sieve import env, trace
from sieve.analysis.lockdebug import named_condition, named_lock
from sieve.backends import make_worker
from sieve.chaos import (
    PROFILE_KINDS,
    SERVICE_REQUEST_KINDS,
    ChaosCrash,
    ChaosSchedule,
    parse_chaos,
)
from sieve.debug import FlightRecorder
from sieve.profile import StackProfiler
from sieve.checkpoint import (
    COLD_SEG_BASE,
    Ledger,
    LedgerMismatch,
    ledger_fingerprint,
)
from sieve.bitset import get_layout
from sieve.enumerate import MAX_HI, primes_in_range
from sieve.metrics import MetricsHistory, MetricsLogger, registry, sample_interval_s
from sieve.service.exemplar import EXEMPLAR_SPAN_RING, ExemplarSampler
from sieve.service.store import TIER_BOUNDARY, StoreSettings, TieredSegmentStore
from sieve.worker import SegmentResult
from sieve.rpc import (
    SUPPORTED_WIRE,
    WIRE_V1,
    WIRE_V2,
    BatchOutcomes,
    FrameDecoder,
    batch_cols_to_items,
    encode_msg,
    encode_msg_v2,
    parse_addr,
    primes_to_cols,
)
from sieve.seed import seed_primes
from sieve.service.index import QueryCtx, SieveIndex

if TYPE_CHECKING:
    from sieve.config import SieveConfig


# --- typed faults ------------------------------------------------------------
# Every non-exact outcome is one of these; the handler maps them 1:1 onto
# typed error replies. Anything else escaping a handler is "internal".


class Overloaded(Exception):
    """Admission refused: queue full or svc_shed injected."""


class DeadlineExceeded(Exception):
    def __init__(self, answered_hi: int, count_so_far: int):
        super().__init__(f"deadline exceeded at {answered_hi}")
        self.answered_hi = answered_hi
        self.count_so_far = count_so_far


class Degraded(Exception):
    """Cold tier unavailable (breaker open / backend_down injected)."""


class BadRequest(Exception):
    pass


class Draining(Exception):
    """Server is draining (SIGTERM / shutdown): new queries are shed."""


class _Demoted(Exception):
    """Internal lane signal, never wire-visible (ISSUE 10): a hot-lane
    request discovered chunks needing a backend dispatch mid-execution.
    The handler re-enqueues the whole request on the cold lane instead
    of holding a hot worker through the dispatch — the registered
    flights are already submitted to the batcher, so the cold re-run
    joins them as a follower (or finds the results cached)."""

    def __init__(self, chunks: int):
        super().__init__(f"demoted to cold lane ({chunks} cold chunk(s))")
        self.chunks = chunks


_ERROR_KIND = {
    Overloaded: "overloaded",
    DeadlineExceeded: "deadline_exceeded",
    Degraded: "degraded",
    BadRequest: "bad_request",
    Draining: "draining",
}


# validated knob readers live in sieve/env.py (ISSUE 15) so every
# plane shares one parse-failure contract; the local names survive
# because the service plane reads them pervasively
_env_int = env.env_int
_env_float = env.env_float


def _env_bool(name: str, default: str) -> bool:
    return env.env_str(name, default) not in ("0", "", "false")


# per-op latency SLOs (ISSUE 12): SIEVE_SVC_SLO_MS_PI=5 reads as
# {"pi": 5.0}; the op name is the env suffix, lowercased
_SLO_ENV_PREFIX = "SIEVE_SVC_SLO_MS_"


def _slo_from_env() -> dict[str, float] | None:
    out: dict[str, float] = {}
    for name, raw in env.env_items():
        if not name.startswith(_SLO_ENV_PREFIX) or name == _SLO_ENV_PREFIX:
            continue
        try:
            out[name[len(_SLO_ENV_PREFIX):].lower()] = float(raw)
        except ValueError:
            raise ValueError(
                f"env {name}={raw!r}: expected a number (milliseconds)"
            ) from None
    return out or None


@dataclasses.dataclass
class ServiceSettings:
    """Service knobs; every default has a ``SIEVE_SVC_*`` env override."""

    queue_limit: int = 64
    workers: int = 4
    default_deadline_s: float = 30.0
    lru_segments: int = 32
    cold_chunk: int = 1 << 22
    cold_cache_entries: int = 4096
    max_primes: int = 200_000
    max_pair_span: int = 10**8
    breaker_fails: int = 3
    breaker_cooldown_s: float = 5.0
    # live follow: ledger poll period (0 disables the follower entirely)
    refresh_s: float = 2.0
    # graceful drain: hard exit budget once draining starts
    drain_s: float = 5.0
    # wire-injectable chaos (the "chaos" message): default OFF — any
    # client could otherwise fault-inject a production server. The CLI
    # spells this --allow-chaos; --chaos-config schedules still apply.
    wire_chaos: bool = False
    # test/chaos knob: extra latency per cold *dispatch* (not per chunk:
    # a batch of N chunks pays it once — exactly the economics batching
    # buys), to simulate a saturated backend deterministically
    cold_delay_s: float = 0.0
    # batched cold plane (ISSUE 9): write cold results back into the
    # ledger (this server becomes the checkpoint dir's designated
    # writer), and cap how many chunks one backend dispatch may carry
    persist_cold: bool = False
    batch_max_chunks: int = 128
    # priority lanes (ISSUE 10): per-lane queue limits (None inherits
    # queue_limit), dedicated hot workers (capped at workers-1 so the
    # cold plane always keeps at least one worker when workers > 1),
    # and the age at which a queued cold item beats fresh hot work
    hot_queue_limit: int | None = None
    cold_queue_limit: int | None = None
    hot_workers: int = 1
    cold_age_s: float = 1.0
    # range sharding (ISSUE 11): anchor this server's served range at a
    # shard lower bound instead of 2. Counts become "primes in
    # [range_lo, v)", nth_prime becomes "k-th prime >= range_lo", and
    # queries below range_lo are typed bad_request naming the range —
    # global-semantics composition is the router's job, never a shard's.
    range_lo: int = 2
    # fleet telemetry (ISSUE 12): ship the bounded span ring piggybacked
    # on terminal replies that ask for it (``telemetry: true`` on the
    # query — the router's merge input). OFF by default: an embedded
    # in-process server shares the host's tracer, and draining it would
    # steal the host's own spans.
    telemetry_ship: bool = False
    # piggyback batching: only attach the ring once this many events are
    # pending (the ``telemetry`` wire op flushes the remainder — the
    # router pulls it when its trace closes). Shipping on EVERY reply
    # would put a serialize on every hot-path request; batching keeps
    # the traced p95 within the 5% overhead budget (bench line 8). The
    # default is half the default ring: ships stay rare enough that a
    # p95 window sees at most one, but the ring never overflows between
    # ships on a steady request stream.
    telemetry_batch: int = 2048
    # per-op latency SLOs: op -> target ms (None = no SLOs). A rolling
    # window of the last slo_window terminal latencies per op; the op
    # "burns" while its window p95 exceeds the target.
    slo_ms: dict[str, float] | None = None
    slo_window: int = 256
    # flight recorder (ISSUE 13): continuous black-box capture (bounded
    # deques — cheap enough to be on by default). debug_dir is where
    # edge triggers (SLO burn, breaker open, crash) freeze timestamped
    # postmortem bundles (None = inline-only, served by the ``debug``
    # wire op); triggers throttle to one bundle per kind per cooldown.
    # metrics_sample_s is the MetricsHistory trend-sampler tick
    # (0 disables the sampler; the env spelling is the metrics-level
    # SIEVE_METRICS_SAMPLE_S, shared with the cluster plane).
    recorder: bool = True
    debug_dir: str | None = None
    debug_cooldown_s: float = 30.0
    metrics_sample_s: float = 1.0
    # wire plane (ISSUE 14): cap on members per ``batch`` wire op (one
    # RPC carrying M point queries), and the per-connection write-queue
    # ceiling — a consumer that stops reading its replies is closed as
    # a slow consumer once this many encoded bytes are parked, so one
    # stuck socket can never balloon the event loop's memory.
    batch_queries: int = 1024
    write_queue_bytes: int = 8 << 20
    # binary wire v2 (ISSUE 16): answer ``hello`` negotiation with the
    # columnar frame capability. False pins every connection to v1 JSON
    # — the mixed-fleet simulation knob and the emergency off-switch;
    # clients detect the downgrade and log one ``wire_downgrade`` event.
    wire_v2: bool = True
    # multi-process serving (ISSUE 17): procs is the fleet size the CLI
    # supervisor spawns (1 = classic single process; the env spelling is
    # SIEVE_SVC_PROCS); proc_index is THIS process's slot in that fleet
    # (set by the supervisor, never from env) — index 0 is the elected
    # writer owning persist-cold and store compaction, every other index
    # runs read-only against the shared store/ledger. reuse_port binds
    # the listener with SO_REUSEPORT so N processes share one port.
    procs: int = 1
    proc_index: int = 0
    reuse_port: bool = False
    # tiered segment store (ISSUE 17): on by default whenever the config
    # has a checkpoint_dir; SIEVE_STORE=0 is the off-switch. The store's
    # own knobs (SIEVE_STORE_FSYNC / _COMPACT_S / _COMPACT_RATIO /
    # _MIN_COMPACT_BYTES / _T2_BYTES / _REFRESH_S) are read by
    # sieve.service.store.StoreSettings.from_env.
    store: bool = True
    # mesh-backed cold plane (ISSUE 18): "mesh" dispatches each cold
    # drain slice as ONE shard_map/jit SPMD launch spanning every device
    # (sieve/backends/mesh_backend.py); "loop" is the classic
    # single-worker path. Mesh init or launch failure falls back to the
    # loop worker — typed (event + counter), never a wrong answer.
    cold_backend: str = "loop"
    # tail-sampled exemplar tracing (ISSUE 19): when on, every request's
    # ctx-carrying spans land in the tracer's exemplar ring, and at
    # completion a sampler decides retention — keep the span tree if the
    # request ended typed-error/shed/degraded/demoted, or its latency
    # exceeded the self-tracked rolling p95 x exemplar_slack (armed only
    # after exemplar_warmup observations), plus a deterministic
    # 1-in-exemplar_baseline healthy baseline. Kept trees go to a
    # bounded in-memory ring (served by the ``exemplars`` wire op) and,
    # under debug_dir, a size-capped rolling exemplars.jsonl.
    exemplars: bool = True
    exemplar_slack: float = 2.0
    exemplar_baseline: int = 100
    exemplar_window: int = 256
    exemplar_warmup: int = 30
    exemplar_ring: int = 256
    exemplar_file_bytes: int = 4 << 20
    # always-on continuous profiler (ISSUE 20): a daemon thread samples
    # sys._current_frames() at prof_hz, folding stacks into a bounded
    # collapsed-stack table (prof_stacks entries, drop-coldest) tagged
    # with thread role and active span. Served by the ``profile`` wire
    # op, snapshotted into every flight-recorder bundle. prof_hz=0
    # disables; prof_idle=True also keeps samples whose leaf is a
    # known parked wait (off by default so shares reflect real work).
    prof_hz: float = 19.0
    prof_stacks: int = 512
    prof_idle: bool = False

    def validate(self) -> "ServiceSettings":
        """Typed startup validation: every rejection names the setting
        (and, via ``from_env``, parse failures name the env variable) —
        a bad knob must fail at startup, never as undefined runtime
        behavior in the admission plane."""
        for name in ("queue_limit", "workers", "batch_max_chunks",
                     "lru_segments", "cold_chunk", "cold_cache_entries",
                     "max_primes", "max_pair_span", "breaker_fails",
                     "batch_queries", "write_queue_bytes",
                     "exemplar_baseline", "exemplar_window",
                     "exemplar_ring", "exemplar_file_bytes",
                     "prof_stacks"):
            v = getattr(self, name)
            if not isinstance(v, int) or isinstance(v, bool) or v <= 0:
                raise ValueError(
                    f"service settings: {name}={v!r} must be a positive "
                    "integer"
                )
        for name in ("hot_queue_limit", "cold_queue_limit"):
            v = getattr(self, name)
            if v is not None and (not isinstance(v, int)
                                  or isinstance(v, bool) or v <= 0):
                raise ValueError(
                    f"service settings: {name}={v!r} must be a positive "
                    "integer (or None to inherit queue_limit)"
                )
        if (not isinstance(self.hot_workers, int)
                or isinstance(self.hot_workers, bool)
                or self.hot_workers < 0):
            raise ValueError(
                f"service settings: hot_workers={self.hot_workers!r} "
                "must be a non-negative integer"
            )
        for name in ("refresh_s", "drain_s", "cold_delay_s", "cold_age_s",
                     "breaker_cooldown_s", "debug_cooldown_s",
                     "metrics_sample_s", "prof_hz"):
            v = getattr(self, name)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v < 0 or not math.isfinite(v):
                raise ValueError(
                    f"service settings: {name}={v!r} must be a "
                    "non-negative number"
                )
        if (not isinstance(self.default_deadline_s, (int, float))
                or isinstance(self.default_deadline_s, bool)
                or self.default_deadline_s <= 0
                or not math.isfinite(self.default_deadline_s)):
            raise ValueError(
                "service settings: default_deadline_s="
                f"{self.default_deadline_s!r} must be a positive number"
            )
        if (not isinstance(self.range_lo, int)
                or isinstance(self.range_lo, bool) or self.range_lo < 2):
            raise ValueError(
                f"service settings: range_lo={self.range_lo!r} must be an "
                "integer >= 2"
            )
        if (not isinstance(self.procs, int) or isinstance(self.procs, bool)
                or self.procs < 1):
            raise ValueError(
                f"service settings: procs={self.procs!r} must be a "
                "positive integer"
            )
        if (not isinstance(self.proc_index, int)
                or isinstance(self.proc_index, bool)
                or not 0 <= self.proc_index < max(self.procs, 1)):
            raise ValueError(
                f"service settings: proc_index={self.proc_index!r} must "
                f"be in [0, procs={self.procs})"
            )
        if (not isinstance(self.slo_window, int)
                or isinstance(self.slo_window, bool) or self.slo_window <= 0):
            raise ValueError(
                f"service settings: slo_window={self.slo_window!r} must be "
                "a positive integer"
            )
        if (not isinstance(self.telemetry_batch, int)
                or isinstance(self.telemetry_batch, bool)
                or self.telemetry_batch < 1):
            raise ValueError(
                f"service settings: telemetry_batch={self.telemetry_batch!r} "
                "must be a positive integer"
            )
        if (not isinstance(self.exemplar_warmup, int)
                or isinstance(self.exemplar_warmup, bool)
                or self.exemplar_warmup < 0):
            raise ValueError(
                f"service settings: exemplar_warmup="
                f"{self.exemplar_warmup!r} must be a non-negative integer"
            )
        if (not isinstance(self.exemplar_slack, (int, float))
                or isinstance(self.exemplar_slack, bool)
                or self.exemplar_slack < 1.0
                or not math.isfinite(self.exemplar_slack)):
            raise ValueError(
                f"service settings: exemplar_slack="
                f"{self.exemplar_slack!r} must be a number >= 1 (the "
                "rolling-p95 multiplier)"
            )
        if self.debug_dir is not None and (
            not isinstance(self.debug_dir, str) or not self.debug_dir
        ):
            raise ValueError(
                f"service settings: debug_dir={self.debug_dir!r} must be a "
                "non-empty path (or None)"
            )
        if self.cold_backend not in ("loop", "mesh"):
            raise ValueError(
                f"service settings: cold_backend={self.cold_backend!r} "
                "must be 'loop' or 'mesh'"
            )
        if self.slo_ms is not None:
            if not isinstance(self.slo_ms, dict):
                raise ValueError(
                    f"service settings: slo_ms={self.slo_ms!r} must be a "
                    "dict of op -> target ms (or None)"
                )
            for op, ms in self.slo_ms.items():
                if (not isinstance(op, str) or not op
                        or not isinstance(ms, (int, float))
                        or isinstance(ms, bool) or ms <= 0
                        or not math.isfinite(ms)):
                    raise ValueError(
                        f"service settings: slo_ms[{op!r}]={ms!r} must map "
                        "an op name to a positive number of milliseconds"
                    )
        return self

    @classmethod
    def from_env(cls, **overrides: Any) -> "ServiceSettings":
        s = cls(
            queue_limit=_env_int("SIEVE_SVC_QUEUE", cls.queue_limit),
            workers=_env_int("SIEVE_SVC_WORKERS", cls.workers),
            default_deadline_s=_env_float(
                "SIEVE_SVC_DEADLINE_S", cls.default_deadline_s
            ),
            lru_segments=_env_int("SIEVE_SVC_LRU", cls.lru_segments),
            cold_chunk=_env_int("SIEVE_SVC_COLD_CHUNK", cls.cold_chunk),
            cold_cache_entries=_env_int(
                "SIEVE_SVC_COLD_CACHE", cls.cold_cache_entries
            ),
            max_primes=_env_int("SIEVE_SVC_MAX_PRIMES", cls.max_primes),
            max_pair_span=_env_int(
                "SIEVE_SVC_MAX_PAIR_SPAN", cls.max_pair_span
            ),
            breaker_fails=_env_int("SIEVE_SVC_BREAKER_FAILS", cls.breaker_fails),
            breaker_cooldown_s=_env_float(
                "SIEVE_SVC_BREAKER_COOLDOWN_S", cls.breaker_cooldown_s
            ),
            refresh_s=_env_float("SIEVE_SVC_REFRESH_S", cls.refresh_s),
            drain_s=_env_float("SIEVE_SVC_DRAIN_S", cls.drain_s),
            wire_chaos=_env_bool("SIEVE_SVC_WIRE_CHAOS", "0"),
            wire_v2=_env_bool("SIEVE_SVC_WIRE_V2", "1"),
            cold_delay_s=_env_float("SIEVE_SVC_COLD_DELAY_S", cls.cold_delay_s),
            persist_cold=_env_bool("SIEVE_SVC_PERSIST_COLD", "0"),
            batch_max_chunks=_env_int(
                "SIEVE_SVC_BATCH_MAX", cls.batch_max_chunks
            ),
            hot_queue_limit=_env_int(
                "SIEVE_SVC_HOT_QUEUE", cls.hot_queue_limit
            ),
            cold_queue_limit=_env_int(
                "SIEVE_SVC_COLD_QUEUE", cls.cold_queue_limit
            ),
            hot_workers=_env_int("SIEVE_SVC_HOT_WORKERS", cls.hot_workers),
            cold_age_s=_env_float("SIEVE_SVC_COLD_AGE_S", cls.cold_age_s),
            range_lo=_env_int("SIEVE_SVC_RANGE_LO", cls.range_lo),
            telemetry_ship=_env_bool("SIEVE_SVC_TELEMETRY", "0"),
            telemetry_batch=_env_int(
                "SIEVE_SVC_TELEMETRY_BATCH", cls.telemetry_batch
            ),
            slo_ms=_slo_from_env(),
            slo_window=_env_int("SIEVE_SVC_SLO_WINDOW", cls.slo_window),
            recorder=_env_bool("SIEVE_SVC_RECORDER", "1"),
            debug_dir=env.env_str("SIEVE_SVC_DEBUG_DIR") or None,
            debug_cooldown_s=_env_float(
                "SIEVE_SVC_DEBUG_COOLDOWN_S", cls.debug_cooldown_s
            ),
            metrics_sample_s=sample_interval_s(),
            batch_queries=_env_int(
                "SIEVE_SVC_BATCH_QUERIES", cls.batch_queries
            ),
            write_queue_bytes=_env_int(
                "SIEVE_SVC_WRITE_QUEUE", cls.write_queue_bytes
            ),
            procs=_env_int("SIEVE_SVC_PROCS", cls.procs),
            reuse_port=_env_bool("SIEVE_SVC_REUSE_PORT", "0"),
            store=_env_bool("SIEVE_STORE", "1"),
            cold_backend=(
                env.env_str("SIEVE_SVC_COLD_BACKEND") or cls.cold_backend
            ),
            exemplars=_env_bool("SIEVE_SVC_EXEMPLARS", "1"),
            exemplar_slack=_env_float(
                "SIEVE_SVC_EXEMPLAR_SLACK", cls.exemplar_slack
            ),
            exemplar_baseline=_env_int(
                "SIEVE_SVC_EXEMPLAR_BASELINE", cls.exemplar_baseline
            ),
            exemplar_window=_env_int(
                "SIEVE_SVC_EXEMPLAR_WINDOW", cls.exemplar_window
            ),
            exemplar_warmup=_env_int(
                "SIEVE_SVC_EXEMPLAR_WARMUP", cls.exemplar_warmup
            ),
            exemplar_ring=_env_int(
                "SIEVE_SVC_EXEMPLAR_RING", cls.exemplar_ring
            ),
            exemplar_file_bytes=_env_int(
                "SIEVE_SVC_EXEMPLAR_FILE_BYTES", cls.exemplar_file_bytes
            ),
            prof_hz=_env_float("SIEVE_PROF_HZ", cls.prof_hz),
            prof_stacks=_env_int("SIEVE_PROF_STACKS", cls.prof_stacks),
            prof_idle=_env_bool("SIEVE_PROF_IDLE", "0"),
        )
        return dataclasses.replace(s, **overrides)


class ColdBackend:
    """Circuit-broken wrapper around the configured SieveWorker backend.

    Computes exact prime counts for cold chunks. Consecutive failures
    (``breaker_fails``) open the breaker for ``breaker_cooldown_s``;
    while open — or while an injected ``backend_down`` window is live —
    every call fails fast with :class:`Degraded` so the worker pool is
    never parked on a dead backend. One lock serializes the backend: it
    models a single saturated compute resource and keeps non-thread-safe
    backends (jax) correct.
    """

    def __init__(self, config: "SieveConfig", settings: ServiceSettings,
                 on_transition=None, chaos=None, events=None, bump=None):
        self.config = config
        self.settings = settings
        self._worker = None  # guard: _lock — lazy; a cold-only
        # server may never need it
        self._lock = named_lock("ColdBackend._lock")
        self._state_lock = named_lock("ColdBackend._state_lock")
        self._fail_streak = 0  # guard: _state_lock
        self._down_until = 0.0  # guard: _state_lock
        self._down_reason = ""  # guard: _state_lock
        self._degraded = False  # guard: _state_lock
        self._on_transition = on_transition or (lambda entering, reason: None)
        # mesh cold plane (ISSUE 18): lazy MeshWorker + typed fallback
        # bookkeeping. A failed mesh INIT is permanent for this process
        # (config/host problem — retrying per drain would pay the failed
        # device probe on every dispatch); a failed LAUNCH falls back
        # per-batch and the next drain tries the mesh again.
        self._mesh_worker = None  # guard: none(written under _lock only;
        # set-once None->worker, lock-free describe() reads are racy-ok)
        self._mesh_failed = None  # guard: none(written under _lock only;
        # set-once None->reason, lock-free describe() reads are racy-ok)
        # observability counters: written only under _lock (count_ranges
        # is the single writer); describe() snapshots them lock-free so
        # stats/health never block behind a long cold dispatch
        self.mesh_launches = 0  # guard: none(written under _lock only;
        # lock-free reads are racy-ok monotonic snapshots)
        self.mesh_fallbacks = 0  # guard: none(written under _lock only;
        # lock-free reads are racy-ok monotonic snapshots)
        self.last_fanout = 0  # guard: none(written under _lock only;
        # lock-free reads are racy-ok snapshots)
        self._chaos = chaos  # injected schedule (svc_mesh_fail draws)
        self._event = events or (lambda kind, **fields: None)
        self._bump = bump or (lambda key, n=1: None)

    def force_down(self, secs: float, reason: str) -> None:
        """Chaos/backend_down: report down for ``secs`` from now."""
        with self._state_lock:
            self._down_until = max(self._down_until, trace.now_s() + secs)
            self._down_reason = reason
        self._update_health()

    def is_down(self) -> tuple[bool, str]:
        with self._state_lock:
            if trace.now_s() < self._down_until:
                return True, self._down_reason
        return False, ""

    @property
    def degraded(self) -> bool:
        self._update_health()
        with self._state_lock:
            return self._degraded

    def _update_health(self) -> None:
        with self._state_lock:
            now_down = trace.now_s() < self._down_until
            if now_down != self._degraded:
                self._degraded = now_down
                reason = self._down_reason if now_down else "recovered"
                transition = (now_down, reason)
            else:
                transition = None
        if transition is not None:
            self._on_transition(*transition)

    def count_range(self, lo: int, hi: int) -> int:
        """Exact primes in [lo, hi) via the backend, or raise Degraded."""
        return int(self.count_ranges([(lo, hi)])[0].count)

    def describe(self) -> dict:
        """Cold-plane identity for stats/health/fleet_top (ISSUE 18):
        the effective backend class, mesh device count, and the last
        drain's chunk fanout — a misconfigured mesh replica (0 devices,
        'loop (mesh failed)') is visible at a glance. Lock-free: these
        are racy-ok snapshots of counters written under _lock."""
        worker = self._mesh_worker
        if worker is not None:
            klass, devices = "mesh", worker.devices
        elif self._mesh_failed is not None:
            klass, devices = "loop (mesh failed)", 0
        else:
            klass, devices = self.settings.cold_backend, 0
        return {
            "cold_backend": klass,
            "mesh_devices": devices,
            "mesh_fanout": self.last_fanout,
        }

    def _mesh_locked(self):
        """Lazily build the MeshWorker. A failed init falls back typed
        (event + counter) ONCE and is then permanent for this process —
        it's a config/host problem, and retrying would pay the failed
        device probe on every drain. Caller holds ``_lock``."""
        if self._mesh_worker is not None:
            return self._mesh_worker
        if self._mesh_failed is not None:
            return None
        try:
            from sieve.backends.mesh_backend import MeshWorker

            self._mesh_worker = MeshWorker(self.config)
        except Exception as e:
            self._mesh_failed = f"mesh init failed: {e}"
            self.mesh_fallbacks += 1
            self._bump("mesh_fallbacks")
            self._event(
                "service_mesh_fallback", reason=self._mesh_failed, chunks=0
            )
            return None
        return self._mesh_worker

    def _mesh_dispatch(self, mesh, chunks, seeds, seg_ids):
        """ONE SPMD launch for the drain slice (ISSUE 18). Returns None
        on launch failure — the caller recomputes the same batch on the
        loop worker, so waiters always get exact answers and the
        degradation is typed (``service_mesh_fallback`` + counter), never
        a wrong answer or a crash. Caller holds ``_lock``."""
        self.mesh_launches += 1
        launch = self.mesh_launches
        t0 = trace.now_s()
        try:
            with trace.span(
                "query.cold_mesh", chunks=len(chunks),
                devices=mesh.devices, launch=launch,
            ):
                if self._chaos is not None and self._chaos.take_kinds(
                    0, launch, ("svc_mesh_fail",)
                ):
                    raise RuntimeError(
                        f"chaos svc_mesh_fail: mesh cold dispatch {launch}"
                    )
                results = mesh.process_segments(
                    chunks, seeds, seg_ids=seg_ids
                )
        except Exception as e:
            self.mesh_fallbacks += 1
            self._bump("mesh_fallbacks")
            self._event(
                "service_mesh_fallback",
                reason=f"mesh launch failed: {e}", chunks=len(chunks),
            )
            return None
        self.last_fanout = len(chunks)
        self._bump("mesh_launches")
        self._event(
            "service_mesh_dispatch", quietable=True, chunks=len(chunks),
            devices=mesh.devices, launch=launch,
            ms=round((trace.now_s() - t0) * 1e3, 3),
        )
        return results

    def count_ranges(self, chunks: list[tuple[int, int]]):
        """One backend dispatch for a sorted list of disjoint chunks
        (ISSUE 9): returns a :class:`~sieve.worker.SegmentResult` per
        chunk (seg_id ``COLD_SEG_BASE + lo`` — the ledger write-back
        key), or raises :class:`Degraded` for the whole batch. The
        ``cold_delay_s`` saturation knob is paid once per dispatch, not
        per chunk — the economics the batch plane exists to buy. One
        failure is ONE breaker strike regardless of batch size."""
        down, reason = self.is_down()
        if down:
            raise Degraded(f"cold backend down: {reason}")
        if self.settings.cold_delay_s > 0:
            # simulated saturation (deterministic chaos/smoke scenarios)
            time.sleep(self.settings.cold_delay_s)
        # one seed set covering the largest hi serves every chunk (a
        # superset of seeds is always safe for a smaller segment)
        seeds = seed_primes(math.isqrt(max(hi for _, hi in chunks) - 1))
        seg_ids = [COLD_SEG_BASE + lo for lo, _ in chunks]
        try:
            with self._lock:
                if self._worker is None:
                    self._worker = make_worker(self.config)
                with trace.span(
                    "query.cold", lo=chunks[0][0], hi=chunks[-1][1],
                    chunks=len(chunks),
                ):
                    results = None
                    if self.settings.cold_backend == "mesh":
                        mesh = self._mesh_locked()
                        if mesh is not None:
                            # None -> typed fallback: the loop path below
                            # recomputes the same batch bit-exactly
                            results = self._mesh_dispatch(
                                mesh, chunks, seeds, seg_ids
                            )
                    if results is None:
                        batch = getattr(
                            self._worker, "process_segments", None
                        )
                        if batch is None:
                            # minimal worker stubs (tests) expose only the
                            # single-segment seam; loop it
                            results = [
                                self._worker.process_segment(
                                    lo, hi, seeds, seg_id=sid
                                )
                                for (lo, hi), sid in zip(chunks, seg_ids)
                            ]
                        else:
                            results = batch(chunks, seeds, seg_ids=seg_ids)
            for res in results:
                if not res.is_sane():
                    raise RuntimeError(
                        f"insane result for chunk [{res.lo}, {res.hi})"
                    )
        except Degraded:
            raise
        except Exception as e:
            with self._state_lock:
                self._fail_streak += 1
                tripped = self._fail_streak >= self.settings.breaker_fails
                if tripped:
                    self._down_until = max(
                        self._down_until,
                        trace.now_s() + self.settings.breaker_cooldown_s,
                    )
                    self._down_reason = f"breaker open ({e})"
                    self._fail_streak = 0
            self._update_health()
            raise Degraded(f"cold backend error: {e}") from e
        with self._state_lock:
            self._fail_streak = 0
        return results

    def close(self) -> None:
        with self._lock:
            if self._worker is not None:
                self._worker.close()
                self._worker = None
            if self._mesh_worker is not None:
                self._mesh_worker.close()
                self._mesh_worker = None


class _Flight:
    """Single-flight slot: waiters block until the batcher resolves the
    chunk with a full SegmentResult (or an error)."""

    __slots__ = ("event", "result", "error")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.result = None  # SegmentResult once resolved ok
        self.error: Exception | None = None


class ColdBatcher:
    """The queue-drain batching point of the cold plane (ISSUE 9).

    Request handlers never call the backend directly any more: they
    register a :class:`_Flight` per missing chunk, submit the keys here,
    and wait. One daemon thread blocks for the first key, then drains
    everything else that queued-up requests have registered in the
    meantime, dedups (single-flight registration already guarantees one
    key per chunk), sorts onto the grid, and issues ONE backend dispatch
    for the whole list via :meth:`ColdBackend.count_ranges` — so M
    concurrent cold queries over K distinct chunks cost at most
    ``ceil(K / batch_max_chunks)`` dispatches. Completed results are
    cached, optionally written back to the ledger
    (:meth:`SieveService._persist_results`), and handed to every waiter.

    ``svc_batch_partial`` chaos keys on :attr:`batches` — the dispatch
    counter, this plane's own "segment" number (like the follower's
    refresh attempts) — and fails one chunk *before* it reaches the
    backend: its waiters get a typed ``degraded`` reply while the rest
    of the batch still answers exact.

    ``_drain_once`` is the whole state machine and is callable directly
    (tests drive it synchronously); the thread only adds the blocking
    loop.
    """

    def __init__(self, service: "SieveService"):
        self.svc = service
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._thread: threading.Thread | None = None
        self.batches = 0  # guard: none(single writer: svc-batcher —
        # the svc_batch_partial dispatch-counter key; tests drive
        # _drain_once synchronously)

    def start(self) -> "ColdBatcher":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="svc-batcher"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._q.put(None)
        if self._thread is not None:
            self._thread.join(timeout=5)

    def submit(self, keys: list[tuple[int, int]]) -> None:
        """Enqueue registered-leader chunk keys as ONE item — a request's
        whole chunk list is never split across drains."""
        self._q.put(list(keys))

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            if self._drain_once(item) == "stop":
                return

    def _drain_once(self, first: list[tuple[int, int]]) -> str:
        """Collect every key list queued behind ``first``, then dispatch
        the sorted distinct set in ``batch_max_chunks``-bounded slices."""
        keys = set(first)
        stop = False
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is None:
                stop = True  # finish the batch in hand, then exit
            else:
                keys.update(item)
        batch = sorted(keys)
        cap = max(1, self.svc.settings.batch_max_chunks)
        for i in range(0, len(batch), cap):
            self._dispatch(batch[i:i + cap])
        return "stop" if stop else "ok"

    def _dispatch(self, batch: list[tuple[int, int]]) -> None:
        svc = self.svc
        self.batches += 1
        t0 = trace.now_s()
        failed: set[int] = set()
        for d in svc.chaos.take_kinds(0, self.batches,
                                      ("svc_batch_partial",)):
            failed.add(int(d["param"] or 0))
        good: list[tuple[int, int]] = []
        for i, key in enumerate(batch):
            if i in failed:
                # per-chunk degradation: only THIS chunk's waiters see a
                # typed degraded reply; the rest of the batch proceeds
                self._resolve(key, None, Degraded(
                    f"chaos svc_batch_partial: chunk [{key[0]}, {key[1]}) "
                    f"failed in batch {self.batches}"
                ))
            else:
                good.append(key)
        n_failed = len(batch) - len(good)
        # tier-1 restart-hot (ISSUE 18): a chunk whose boundary entry a
        # previous incarnation persisted through the store answers from
        # disk — no re-marking across restarts. Only boundary-or-richer
        # tiers qualify (counts alone can't rebuild a SegmentResult).
        if good:
            hits: list[tuple[tuple[int, int], SegmentResult]] = []
            misses: list[tuple[int, int]] = []
            for key in good:
                res = svc._store_cold_result(key)
                if res is None:
                    misses.append(key)
                else:
                    hits.append((key, res))
            if hits:
                svc._bump("cold_store_hits", len(hits))
                with svc._cold_lock:
                    for _key, res in hits:
                        svc._cold_cache[(res.lo, res.hi)] = res
                        svc._cold_cache.move_to_end((res.lo, res.hi))
                    while (len(svc._cold_cache)
                           > svc.settings.cold_cache_entries):
                        svc._cold_cache.popitem(last=False)
                for key, res in hits:
                    self._resolve(key, res, None)
            good = misses
        persisted = 0
        if good:
            svc._bump("cold_dispatches")
            svc._bump("cold_batched_chunks", len(good))
            svc._bump("cold_computes", len(good))
            try:
                with trace.span("query.cold_batch", chunks=len(good),
                                lo=good[0][0], hi=good[-1][1]):
                    results = svc.cold.count_ranges(good)
            except Exception as e:  # Degraded or internal: whole dispatch
                for key in good:
                    self._resolve(key, None, e)
            else:
                persisted = svc._persist_results(results)
                with svc._cold_lock:
                    for res in results:
                        svc._cold_cache[(res.lo, res.hi)] = res
                        svc._cold_cache.move_to_end((res.lo, res.hi))
                    while (len(svc._cold_cache)
                           > svc.settings.cold_cache_entries):
                        svc._cold_cache.popitem(last=False)
                for key, res in zip(good, results):
                    self._resolve(key, res, None)
        ms = round((trace.now_s() - t0) * 1000, 3)
        registry().histogram("service.batch_chunks").observe(len(good))
        svc.metrics.event(
            "service_batched", quietable=True, chunks=len(good),
            lo=batch[0][0], hi=batch[-1][1], ms=ms,
            persisted=persisted, failed=n_failed,
        )

    def _resolve(self, key, result, error) -> None:
        svc = self.svc
        with svc._cold_lock:
            flight = svc._inflight.pop(key, None)
        if flight is None:
            return  # cancelled/raced away; the result is still cached
        flight.result = result
        flight.error = error
        flight.event.set()


class LedgerFollower:
    """Live-follow the ledger a concurrent coordinator is extending.

    A daemon thread stats the ledger file every ``refresh_s``; when the
    fingerprint (mtime + size) moves it re-opens read-only, verifies the
    checksum, builds a fresh :class:`SieveIndex` that *inherits the old
    BitsetLRU*, and swaps it in with one reference assignment — readers
    that captured the previous index finish on it untouched. Invariants:

    * ``covered_hi`` is monotonic per process: a snapshot that would
      shrink coverage (the coordinator's quarantine window, a rewritten
      or foreign ledger) is a skipped refresh, never a swap.
    * a corrupt / mid-quarantine / vanished read is a skipped refresh
      with a ``service_refresh_failed`` event — never a crash; the stale
      fingerprint is dropped so the very next poll retries.
    * each swap emits ``service_refreshed`` + the ``cluster.covered_hi``
      gauge and a ``service.refresh`` trace span.

    ``poll_once`` is the whole state machine and is callable directly
    (tests drive it synchronously); the thread only adds the timer.
    """

    def __init__(self, service: "SieveService", refresh_s: float):
        self.service = service
        self.refresh_s = refresh_s
        self._path = service.ledger_path
        assert self._path is not None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._poll_lock = named_lock("LedgerFollower._poll_lock")
        self._last_fp = ledger_fingerprint(self._path)
        self._last_checksum = (
            service.ledger.checksum if service.ledger is not None else None
        )
        self.attempts = 0  # guard: none(single writer: svc-follower —
        # refresh *attempts*, the svc_refresh_corrupt key)

    def start(self) -> "LedgerFollower":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="svc-follower"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self.refresh_s):
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — the follower never dies
                self._failed(trace.now_s(), f"unexpected: {e!r}")

    def poll_once(self) -> str:
        """One poll step; returns "unchanged" / "swapped" / "failed"."""
        with self._poll_lock:
            return self._poll_locked()

    def _poll_locked(self) -> str:
        svc = self.service
        # Non-writer processes learn about new store generations (post-
        # compaction pointer swaps) and freshly appended peer demotions
        # here, on the same cadence as ledger follows.  Independent of
        # the ledger fingerprint: peer appends don't touch the ledger.
        if svc.store is not None:
            try:
                svc.store.maybe_refresh()
            except Exception:  # noqa: BLE001 — the follower never dies
                pass
        fp = ledger_fingerprint(self._path)
        if fp == self._last_fp:
            return "unchanged"
        self.attempts += 1
        t0 = trace.now_s()
        if svc.chaos.take_kinds(0, self.attempts, ("svc_refresh_corrupt",)):
            self._failed(t0, "chaos svc_refresh_corrupt injected")
            return "failed"
        try:
            led = svc._open_snapshot()
        except (LedgerMismatch, OSError, ValueError) as e:
            self._failed(t0, f"{type(e).__name__}: {e}")
            return "failed"
        if led.checksum == self._last_checksum:
            self._last_fp = fp  # atomic rewrite of identical content
            return "unchanged"
        old = svc.index
        new = SieveIndex(
            svc.config.packing, led.completed(),
            svc.settings.lru_segments, lru=old.lru, base=old.base,
            store=old.store,
        )
        if new.covered_hi < old.covered_hi:
            self._failed(
                t0,
                f"covered_hi would regress {old.covered_hi} -> "
                f"{new.covered_hi} (mid-quarantine or rewritten ledger); "
                "keeping the previous snapshot",
            )
            return "failed"
        # THE swap: one reference assignment. In-flight queries hold the
        # old index (captured at admission) and finish on it; new
        # requests see the new one. Never mutate an index in place.
        svc.index = new
        svc.ledger = led
        svc._snapshot_ts = trace.now_s()
        svc._refreshes += 1
        self._last_fp = fp
        self._last_checksum = led.checksum
        registry().gauge("cluster.covered_hi").set(float(new.covered_hi))
        svc.metrics.event(
            "service_refreshed",
            covered_hi=new.covered_hi,
            prev_covered_hi=old.covered_hi,
            segments=len(new.segments),
            refreshes=svc._refreshes,
        )
        trace.add_span(
            "service.refresh", t0, trace.now_s() - t0,
            outcome="swapped", covered_hi=new.covered_hi,
            prev_covered_hi=old.covered_hi,
        )
        return "swapped"

    def _failed(self, t0: float, reason: str) -> None:
        svc = self.service
        svc._refresh_failed += 1
        self._last_fp = None  # retry on the very next poll
        svc.metrics.event("service_refresh_failed", reason=reason)
        registry().counter("service.refresh_failed").inc()
        trace.add_span(
            "service.refresh", t0, trace.now_s() - t0,
            outcome="failed", reason=reason,
        )


_STATS = (
    "requests",
    "index_hits",
    "cold_computes",
    "cold_cache_hits",
    "cold_dispatches",
    "cold_batched_chunks",
    "cold_persisted",
    "cold_store_hits",
    "mesh_launches",
    "mesh_fallbacks",
    "coalesced",
    "shed",
    "hot_admitted",
    "cold_admitted",
    "demoted",
    "lane_shed_hot",
    "lane_shed_cold",
    "deadline_exceeded",
    "degraded_replies",
    "draining_replies",
    "bad_requests",
    "internal_errors",
    "telemetry_replies",
    "trace_drops",
    "batch_requests",
    "batch_members",
    "slow_consumer_closed",
    "wire_v2_conns",
    "exemplars_seen",
    "exemplars_kept",
    "profile_pulls",
    "profile_gaps",
)


# --- wire event loop (ISSUE 14) ----------------------------------------------

# event-loop tick for throttled (svc_slow_frame) connections: a dribbled
# write queue drains in bytes-per-tick slices at this cadence while every
# other connection keeps full-speed service
_TICK_S = 0.005


class _Conn:
    """Per-connection state owned by the wire event loop.

    The loop thread does all reads; reply frames are appended under
    ``lock`` and either flushed directly by the replying thread (idle
    queue, ``tx`` serializes the socket) or left for the woken loop.
    ``head_off`` tracks how much of the queue's head frame has hit the
    socket, so a front-inserted inline reply (health/stats/metrics/
    debug) can jump the queue without ever interleaving into a
    partially-sent frame.
    """

    __slots__ = ("sock", "decoder", "wq", "head_off", "wq_bytes", "lock",
                 "tx", "sending", "closed", "kill", "throttle_bps",
                 "next_t", "mask", "wire_v")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.decoder = FrameDecoder()
        self.wq: collections.deque[bytes] = collections.deque()
        self.head_off = 0  # guard: lock
        self.wq_bytes = 0  # guard: lock
        self.lock = named_lock("_Conn.lock")
        # serializes actual socket sends: the loop's flush, throttled
        # ticks, and a worker's opportunistic direct send never
        # interleave bytes on the wire
        self.tx = named_lock("_Conn.tx")
        # True while a send of the head frame is in flight — head_off
        # only records progress AFTER send() returns, so a front-insert
        # must also treat an invisible whole-frame send as "the head is
        # spoken for" or the sender's popleft destroys the wrong frame
        self.sending = False  # guard: lock
        self.closed = False  # guard: lock
        # set by writers that cannot touch the selector (slow-consumer
        # overflow): the loop reaps killed conns on its next wakeup
        self.kill = False  # guard: lock
        # svc_slow_frame chaos: reply bytes per _TICK_S (0 = full speed)
        self.throttle_bps = 0.0  # guard: none(written only by the
        # wire thread; locked worker reads see a current-or-older
        # budget, both safe)
        self.next_t = 0.0
        self.mask = 0  # selector interest currently registered
        # negotiated wire version for frames WE send on this conn
        # (ISSUE 16). Starts at the v1 JSON floor; the hello handshake
        # raises it before the client pipelines its first v2-era query.
        self.wire_v = WIRE_V1  # guard: none(written once by the wire
        # thread on hello, strictly before any reply that could observe
        # it is enqueued; workers only ever read)

    def pending(self) -> bool:
        with self.lock:
            return bool(self.wq)


class SieveService:
    """The persistent query server. See the module docstring for tiers."""

    def __init__(
        self,
        config: "SieveConfig",
        settings: ServiceSettings | None = None,
        addr: str | None = None,
    ):
        self.config = config
        self.settings = (settings or ServiceSettings.from_env()).validate()
        self._addr_req = addr or "127.0.0.1:0"
        self.metrics = MetricsLogger(config)
        entries = {}
        self.ledger = None  # guard: none(reference swap by
        # svc-follower; readers take one snapshot per message)
        if config.checkpoint_dir:
            self.ledger = self._open_snapshot()
            entries = self.ledger.completed()
        self.chaos = ChaosSchedule(config.chaos_directives())
        # tiered segment store (ISSUE 17): mmap'd tiers under the
        # checkpoint dir, shared by every --procs sibling through the
        # page cache. proc 0 is the elected writer (tier-0 ledger
        # import + background compaction); every process appends
        # demotions and follows generations. SIEVE_STORE=0 disables.
        self.store: TieredSegmentStore | None = None  # guard: none(set
        # once at construction; readers null-check)
        if config.checkpoint_dir and self.settings.store:
            self.store = TieredSegmentStore(
                os.path.join(config.checkpoint_dir, "store"),
                writer=(self.settings.proc_index == 0),
                settings=StoreSettings.from_env(),
                chaos=self.chaos,
                events=self.metrics.event,
            )
            if self.store.writer and self.ledger is not None:
                self.store.import_ledger(self.ledger.store_tier0_entries())
        # range sharding (ISSUE 11): the index anchors its contiguous
        # prefix at range_lo, so this server natively speaks shard-local
        # semantics (counts from range_lo, nth >= range_lo)
        self.base = self.settings.range_lo
        self.index = SieveIndex(  # guard: none(follower reference
            # swap; readers take one snapshot per message)
            config.packing, entries, self.settings.lru_segments,
            base=self.base, store=self.store,
        )
        registry().gauge("cluster.covered_hi").set(
            float(self.index.covered_hi)
        )
        self._snapshot_ts = trace.now_s()  # guard: none(single
        # writer: svc-follower; float reads are GIL-atomic)
        self._refreshes = 0  # guard: none(single writer: svc-follower)
        self._refresh_failed = 0  # guard: none(single writer:
        # svc-follower)
        self.follower: LedgerFollower | None = None  # guard: none(set
        # once in start(); readers null-check)
        self.cold = ColdBackend(
            config, self.settings, self._on_degraded,
            chaos=self.chaos, events=self.metrics.event, bump=self._bump,
        )
        self._cold_lock = named_lock("SieveService._cold_lock")
        # LRU of chunk results, most-recent at the end: O(1) hit
        # (move_to_end) and O(1) eviction (popitem(last=False)) — the
        # dict+list pair this replaces paid O(n) per eviction
        self._cold_cache: "collections.OrderedDict" = (  # guard: _cold_lock
            collections.OrderedDict())
        self._inflight: dict[tuple[int, int], _Flight] = {}  # guard: _cold_lock
        self.batcher = ColdBatcher(self)
        # --persist-cold: this server owns the checkpoint dir's ledger
        # as a writer; only the batcher thread ever records into it
        self._writer: Ledger | None = None
        # writer election (ISSUE 17): in a --procs fleet only proc 0
        # may own the ledger as a writer — readers keep persist_cold
        # semantics through the shared store + ledger follow instead
        if self.settings.persist_cold and config.checkpoint_dir \
                and self.settings.proc_index == 0:
            self._writer = Ledger.open(config)
        # priority lanes (ISSUE 10): two bounded deques under one
        # condition. Dedicated hot workers only ever pull "hot"; shared
        # workers prefer hot unless the cold head has aged past
        # cold_age_s (cold is delayed, never starved). workers == 1
        # degenerates to a single shared hot-preferring worker — a
        # reservation would otherwise starve the cold lane outright.
        s = self.settings
        self._hot_limit = (s.hot_queue_limit if s.hot_queue_limit is not None
                           else s.queue_limit)
        self._cold_limit = (s.cold_queue_limit
                            if s.cold_queue_limit is not None
                            else s.queue_limit)
        self._dedicated_hot = (min(s.hot_workers, s.workers - 1)
                               if s.workers > 1 else 0)
        self._lanes: dict[str, collections.deque] = {
            "hot": collections.deque(), "cold": collections.deque(),
        }
        self._lane_cond = named_condition("SieveService._lane_cond")
        self._stopping = False  # guard: _lane_cond
        self._seq = 0  # guard: _seq_lock
        self._seq_lock = named_lock("SieveService._seq_lock")
        self._stats = {k: 0 for k in _STATS}  # guard: _stats_lock
        self._stats_lock = named_lock("SieveService._stats_lock")
        self._threads: list[threading.Thread] = []
        self._conns: set[_Conn] = set()  # guard: _conns_lock
        self._conns_lock = named_lock("SieveService._conns_lock")
        self._listener: socket.socket | None = None  # guard: none(set
        # once in start() before the loop thread exists; drain/stop
        # only call shutdown(), never rebind)
        self._bound_addr: str | None = None
        self._closing = False  # guard: none(monotonic stop flag;
        # bool reads are GIL-atomic)
        # wire event loop (ISSUE 14): self-wake pipe so worker threads
        # (and drain/stop) can nudge the selector out of its wait
        self._wake_r: socket.socket | None = None  # guard: none(set
        # once in start() before the loop thread exists)
        self._wake_w: socket.socket | None = None  # guard: none(set
        # once in start() before the loop thread exists)
        # graceful drain (ISSUE 8): _inflight_n counts admitted-but-not-
        # replied queries; drain_event fires when draining starts, and
        # _drained once the last in-flight reply is out
        self._draining = False  # guard: none(monotonic drain flag;
        # a racy reader sheds at most one extra request)
        self._inflight_n = 0  # guard: _inflight_lock
        self._inflight_lock = named_lock("SieveService._inflight_lock")
        self.drain_event = threading.Event()
        self._drained = threading.Event()
        # replica_down chaos: while live, every connection is dropped
        # without a reply — a dead replica from the client's side
        self._replica_down_until = 0.0  # guard: none(wire-thread
        # only: the chaos admit path writes and _read_ready reads,
        # both on svc-wire)
        # per-op SLO tracking (ISSUE 12): rolling latency windows and
        # the set of ops currently burning (p95 over target) — the burn
        # *transition* is the event, the gauge is the live level
        self._slo_lock = named_lock("SieveService._slo_lock")
        self._slo_windows: dict[str, collections.deque] = {}  # guard: _slo_lock
        self._slo_burning: set[str] = set()  # guard: _slo_lock
        # telemetry shipping: armed in start() when telemetry_ship is on
        self._telemetry_on = False  # guard: none(armed once in
        # start(); bool reads are GIL-atomic)
        # flight recorder (ISSUE 13): trend sampler + black-box capture,
        # armed in start(); edge triggers (SLO burn, breaker open,
        # crash) freeze bundles under settings.debug_dir
        # continuous profiler (ISSUE 20): low-rate stack sampler feeding
        # the ``profile`` wire op and every recorder bundle; built before
        # the recorder so bundles can embed its snapshot
        self.profiler: StackProfiler | None = None
        if self.settings.prof_hz > 0:
            self.profiler = StackProfiler(
                "service",
                hz=self.settings.prof_hz,
                max_stacks=self.settings.prof_stacks,
                include_idle=self.settings.prof_idle,
            )
        self._prof_pulls = 0  # guard: none(wire-thread only: the
        # profile op is dispatched inline on svc-wire)
        self.history: MetricsHistory | None = None
        self.recorder: FlightRecorder | None = None
        if self.settings.recorder:
            self.history = MetricsHistory(
                sample_s=self.settings.metrics_sample_s
            )
            self.recorder = FlightRecorder(
                "service",
                debug_dir=self.settings.debug_dir,
                history=self.history,
                config=config,
                logger=self.metrics,
                cooldown_s=self.settings.debug_cooldown_s,
                profiler=self.profiler,
            )
        # tail-sampled exemplars (ISSUE 19): completion-time retention of
        # span trees — errors/demotions always, the slow tail past the
        # sampler's own rolling p95 x slack, and a 1-in-N healthy
        # baseline. Served inline by the ``exemplars`` wire op; persisted
        # to a rolling exemplars.jsonl when debug_dir is set.
        self.exemplar: ExemplarSampler | None = None
        if self.settings.exemplars:
            self.exemplar = ExemplarSampler(
                "service",
                slack=self.settings.exemplar_slack,
                baseline=self.settings.exemplar_baseline,
                window=self.settings.exemplar_window,
                warmup=self.settings.exemplar_warmup,
                ring=self.settings.exemplar_ring,
                file_bytes=self.settings.exemplar_file_bytes,
                debug_dir=self.settings.debug_dir,
                logger=self.metrics,
            )

    # --- lifecycle -------------------------------------------------------

    @property
    def addr(self) -> str:
        # cached at bind time: drain() closes the listener but the bound
        # address must stay queryable while connections finish
        assert self._bound_addr is not None, "service not started"
        return self._bound_addr

    @property
    def ledger_path(self):
        if not self.config.checkpoint_dir:
            return None
        from pathlib import Path

        from sieve.checkpoint import LEDGER_NAME

        return Path(self.config.checkpoint_dir) / LEDGER_NAME

    def _open_snapshot(self) -> Ledger:
        """Read-only ledger open + the v1-compat warning event: a
        checksum-less version-1 file loads, but never silently."""
        led = Ledger.open_readonly(self.config)
        if led.unverified:
            self.metrics.event("ledger_unverified", path=str(led.path))
        return led

    def start(self) -> "SieveService":
        host, port = parse_addr(self._addr_req)
        # SO_REUSEPORT (ISSUE 17): N sibling processes bind the same
        # port and the kernel load-balances connections across them
        self._listener = socket.create_server(
            (host, port), reuse_port=self.settings.reuse_port)
        self._listener.listen(64)
        bhost, bport = self._listener.getsockname()[:2]
        self._bound_addr = f"{bhost}:{bport}"
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        t = threading.Thread(target=self._wire_loop, daemon=True,
                             name="svc-wire")
        t.start()
        self._threads.append(t)
        for i in range(self.settings.workers):
            dedicated = i < self._dedicated_hot
            w = threading.Thread(
                target=self._worker_loop, args=(dedicated,), daemon=True,
                name=f"svc-worker-{'hot-' if dedicated else ''}{i}",
            )
            w.start()
            self._threads.append(w)
        self.batcher.start()
        if self.store is not None:
            self.store.start()  # writer: background compactor
        if self.config.checkpoint_dir and self.settings.refresh_s > 0:
            self.follower = LedgerFollower(
                self, self.settings.refresh_s
            ).start()
        if self.settings.telemetry_ship:
            # same ship ring as a cluster worker: bounded drop-oldest
            # capture, drained onto terminal replies that ask for it
            from sieve.worker import telemetry_ring_size

            ring = telemetry_ring_size()
            if ring > 0:
                tr = trace.get_tracer()
                tr.set_event_limit(ring)
                tr.enable(clear=False)
                self._telemetry_on = True
        if self.recorder is not None:
            self.history.start()
            self.recorder.install()
        if self.profiler is not None:
            self.profiler.start()
        if self.exemplar is not None:
            # arm the process tracer's exemplar span ring (independent of
            # full event capture — ``trace.enable`` stays off)
            trace.get_tracer().exemplar_enable(EXEMPLAR_SPAN_RING)
        return self

    def drain(self) -> None:
        """Flip to draining: stop accepting, answer queued work, shed new
        queries as typed ``draining``. Idempotent; SIGTERM, the wire
        ``shutdown`` message, and the ``svc_drain`` chaos kind all land
        here."""
        if self._draining:
            return
        self._draining = True
        if self._listener is not None:
            # shutdown only — connects are refused immediately, but the
            # fd stays open until the event loop unregisters it from the
            # selector (closing here would free the fd while its selector
            # registration is live; an accepted connection reusing the
            # number would then collide on register)
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        self._wake()
        hot, cold = self._lane_depths()
        with self._inflight_lock:
            inflight = self._inflight_n
        self.metrics.event("service_drain", queued=hot + cold,
                           inflight=inflight)
        registry().gauge("service.draining").set(1.0)
        self.drain_event.set()
        self._maybe_drained()

    def wait_drained(self, timeout: float | None = None) -> bool:
        """Block until every admitted query has been answered (True), or
        the timeout expired with work still in flight (False)."""
        return self._drained.wait(timeout)

    def _maybe_drained(self) -> None:
        with self._inflight_lock:
            done = self._draining and self._inflight_n == 0
        if done:
            self._drained.set()

    def stop(self) -> None:
        self._closing = True
        if self.follower is not None:
            self.follower.stop()
        if self._listener is not None:
            # shutdown only; the event loop owns the close (see drain)
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        self._wake()
        with self._lane_cond:
            self._stopping = True
            self._lane_cond.notify_all()
        for t in self._threads:
            t.join(timeout=5)
        # the loop's exit path closes every conn; cover a wedged loop too
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.sock.close()
            except OSError:
                pass
        self.batcher.stop()
        self.cold.close()
        if self.store is not None:
            self.store.close()
        if self.exemplar is not None:
            self.exemplar.close()
        if self.profiler is not None:
            self.profiler.stop()
        if self.recorder is not None:
            self.recorder.uninstall()
            self.history.stop()
        self._drained.set()

    def __enter__(self) -> "SieveService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # --- bookkeeping -----------------------------------------------------

    def _bump(self, name: str, n: int = 1) -> None:
        with self._stats_lock:
            self._stats[name] += n
        registry().counter(f"service.{name}").inc(n)

    # --- SLO tracking (ISSUE 12) ------------------------------------------

    def _observe_slo(self, op: str, elapsed_ms: float) -> None:
        """Fold one terminal latency into the op's rolling window. The
        ``service.slo_burn`` gauge carries the worst burn ratio across
        ops (p95/target; >1 means out of SLO); the ``service_slo_burn``
        event fires on the transition INTO burn, not per request."""
        slo = self.settings.slo_ms
        if not slo:
            return
        target = slo.get(op)
        if target is None:
            return
        with self._slo_lock:
            win = self._slo_windows.get(op)
            if win is None:
                win = self._slo_windows[op] = collections.deque(
                    maxlen=self.settings.slo_window
                )
            win.append(elapsed_ms)
            vals = sorted(win)
            p95 = vals[max(0, math.ceil(0.95 * len(vals)) - 1)]
            burn = p95 / target
            newly = burn > 1.0 and op not in self._slo_burning
            if burn > 1.0:
                self._slo_burning.add(op)
            else:
                self._slo_burning.discard(op)
            worst = max(
                (self._win_burn_locked(o) for o in self._slo_windows),
                default=0.0,
            )
        reg = registry()
        reg.gauge(f"service.slo_burn.{op}").set(round(burn, 4))
        reg.gauge("service.slo_burn").set(round(worst, 4))
        if newly:
            self.metrics.event(
                "service_slo_burn", op=op, p95_ms=round(p95, 3),
                slo_ms=target, window=len(vals),
            )
            if self.recorder is not None:
                self.recorder.trigger(
                    "slo_burn", op=op, p95_ms=round(p95, 3), slo_ms=target,
                )

    def _win_burn_locked(self, op: str) -> float:  # holds: _slo_lock
        win = self._slo_windows.get(op)
        target = (self.settings.slo_ms or {}).get(op)
        if not win or not target:
            return 0.0
        vals = sorted(win)
        return vals[max(0, math.ceil(0.95 * len(vals)) - 1)] / target

    def slo_snapshot(self) -> dict:
        """Per-op SLO state for stats/fleet_top. An op with zero
        observations reports ``p95_ms: None`` — a cold server has no
        percentile, and null must never masquerade as a 0 ms p95."""
        slo = self.settings.slo_ms or {}
        out: dict[str, dict] = {}
        with self._slo_lock:
            for op, target in sorted(slo.items()):
                win = self._slo_windows.get(op)
                vals = sorted(win) if win else []
                p95 = (vals[max(0, math.ceil(0.95 * len(vals)) - 1)]
                       if vals else None)
                out[op] = {
                    "slo_ms": target,
                    "p95_ms": round(p95, 3) if p95 is not None else None,
                    "n": len(vals),
                    "burn": round(p95 / target, 4) if p95 is not None
                    else None,
                    "burning": op in self._slo_burning,
                }
        return out

    # --- lanes (ISSUE 10) -------------------------------------------------

    def _lane_depths(self) -> tuple[int, int]:
        with self._lane_cond:
            return len(self._lanes["hot"]), len(self._lanes["cold"])

    def _brownout_locked(self) -> bool:
        # brownout: the hot lane is backlogged past half its limit —
        # sustained overload where the cold lane must shed first so hot
        # answers stay exact
        return len(self._lanes["hot"]) >= max(1, self._hot_limit // 2)

    def brownout(self) -> bool:
        with self._lane_cond:
            return self._brownout_locked()

    def _lane_limit_locked(self, lane: str) -> int:
        if lane == "hot":
            return self._hot_limit
        if self._brownout_locked():
            return max(1, self._cold_limit // 2)
        return self._cold_limit

    def _set_depth_gauges(self, hot: int, cold: int) -> None:
        reg = registry()
        reg.gauge("service.queue_depth").set(float(hot + cold))
        reg.gauge("service.queue_depth.hot").set(float(hot))
        reg.gauge("service.queue_depth.cold").set(float(cold))

    def _lane_put(self, lane: str, item: tuple) -> bool:
        """Bounded per-lane admission; False means the caller must shed
        typed ``overloaded`` (the cold limit halves under brownout)."""
        with self._lane_cond:
            if self._stopping:
                return False
            q = self._lanes[lane]
            if len(q) >= self._lane_limit_locked(lane):
                return False
            q.append(item)
            hot = len(self._lanes["hot"])
            cold = len(self._lanes["cold"])
            self._lane_cond.notify_all()
        self._set_depth_gauges(hot, cold)
        return True

    def _next_item(self, dedicated: bool):
        """Pull the next request for one worker. Dedicated workers serve
        only the hot lane (the reservation that keeps ColdBatcher floods
        out of the whole pool); shared workers prefer hot unless the
        cold head has waited >= cold_age_s — an aged cold item beats
        fresh hot work, so cold is delayed, never starved."""
        with self._lane_cond:
            while True:
                hot = self._lanes["hot"]
                cold = self._lanes["cold"]
                item = None
                if dedicated:
                    if hot:
                        item = hot.popleft()
                elif hot and cold:
                    aged = (trace.now_s() - cold[0][2]
                            >= self.settings.cold_age_s)
                    item = cold.popleft() if aged else hot.popleft()
                elif hot:
                    item = hot.popleft()
                elif cold:
                    item = cold.popleft()
                if item is not None:
                    h, c = len(hot), len(cold)
                    self._set_depth_gauges(h, c)
                    return item
                if self._stopping:
                    return None
                # timed wait: an aging cold head must be re-examined even
                # if no new put ever notifies
                self._lane_cond.wait(0.05)

    def stats(self) -> dict:
        with self._stats_lock:
            out = dict(self._stats)
        out.update(self.index.stats())
        hot, cold = self._lane_depths()
        out["queue_depth"] = hot + cold
        out["queue_depth_hot"] = hot
        out["queue_depth_cold"] = cold
        out["brownout"] = self.brownout()
        out["hot_workers_dedicated"] = self._dedicated_hot
        out["degraded"] = self.cold.degraded
        out["refreshes"] = self._refreshes
        out["refresh_failed"] = self._refresh_failed
        out["refresh_attempts"] = (
            self.follower.attempts if self.follower is not None else 0
        )
        out["snapshot_age_s"] = round(trace.now_s() - self._snapshot_ts, 3)
        out["draining"] = self._draining
        out["persist_cold"] = self._writer is not None
        # cold-plane identity (ISSUE 18): effective backend class, mesh
        # device count, last drain's chunk fanout — lock-free snapshot
        out.update(self.cold.describe())
        out["range_lo"] = self.base
        out["procs"] = self.settings.procs
        out["proc_index"] = self.settings.proc_index
        # store.stats() is in-memory only (no I/O, no flock) so it is
        # safe from the inline stats op on the wire loop
        out["store"] = self.store.stats() if self.store is not None else None
        out["slo"] = self.slo_snapshot()
        return out

    def _on_degraded(self, entering: bool, reason: str) -> None:
        self.metrics.event("service_degraded", entering=entering,
                           reason=reason)
        registry().gauge("service.degraded").set(1.0 if entering else 0.0)
        if entering and self.recorder is not None:
            # circuit breaker opened: the minutes before are exactly
            # what a postmortem needs — freeze them now
            self.recorder.trigger("breaker_open", reason=reason)

    def inject_chaos(self, spec: str) -> int:
        """Extend the live schedule (the ``chaos`` wire op / tests)."""
        ds = parse_chaos(spec)
        self.chaos.extend(ds)
        return len(ds)

    # --- wire event loop (ISSUE 14) --------------------------------------

    def _wake(self) -> None:
        """Nudge the selector out of its wait (worker reply enqueued, a
        kill flagged, drain/stop). Safe from any thread."""
        w = self._wake_w
        if w is None:
            return
        try:
            w.send(b"\0")
        except (BlockingIOError, OSError):
            pass  # pipe full or closed: the loop is waking anyway

    def _wire_loop(self) -> None:
        """The selector event loop: one thread owns every socket.

        Non-blocking reads stream through each connection's incremental
        :class:`FrameDecoder`, so any number of pipelined requests ride
        one socket and a peer dribbling a frame byte-by-byte costs one
        buffer append per tick, never a parked thread. Inline ops are
        answered right here (front-inserted into the write queue, ahead
        of any queued query replies); admitted queries flow through the
        unchanged lane/worker plane, whose replies come back via
        :meth:`_reply` + the wake pipe. Writes are flushed on
        write-readiness per connection — svc_slow_frame connections
        instead drain bytes-per-tick on a timer — so one slow consumer
        never head-of-line-blocks another connection's replies."""
        sel = selectors.DefaultSelector()
        listener = self._listener
        assert listener is not None and self._wake_r is not None
        listener.setblocking(False)
        sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        sel.register(listener, selectors.EVENT_READ, "accept")
        listener_live = True
        try:
            while not self._closing:
                if listener_live and self._draining:
                    listener_live = False
                    try:
                        sel.unregister(listener)
                        listener.close()
                    except (KeyError, ValueError, OSError):
                        pass
                timeout = self._refresh_interest(sel)
                for key, ev in sel.select(timeout):
                    if key.data == "wake":
                        try:
                            while self._wake_r.recv(4096):
                                pass
                        except (BlockingIOError, OSError):
                            pass
                    elif key.data == "accept":
                        listener_live = self._accept_ready(sel, listener)
                    else:
                        c = key.data
                        if ev & selectors.EVENT_READ and not c.closed:
                            self._read_ready(sel, c)
                        if ev & selectors.EVENT_WRITE and not c.closed:
                            if not self._flush(c):
                                self._close_conn(sel, c)
                self._tick_throttled(sel)
        finally:
            with self._conns_lock:
                conns = list(self._conns)
            for c in conns:
                self._close_conn(sel, c)
            if listener_live:
                try:
                    listener.close()
                except OSError:
                    pass
            for s in (self._wake_r, self._wake_w):
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
            sel.close()

    def _refresh_interest(self, sel) -> float:
        """Reap killed conns, sync each conn's selector mask with its
        queue state, and pick the select timeout (a short tick while a
        throttled connection still has bytes to dribble)."""
        timeout = 0.2
        now = time.monotonic()
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            if c.kill or c.closed:
                self._close_conn(sel, c)
                continue
            throttled = c.throttle_bps > 0
            with c.lock:
                pending = bool(c.wq)
            desired = selectors.EVENT_READ
            if pending and not throttled:
                desired |= selectors.EVENT_WRITE
            if desired != c.mask:
                try:
                    sel.modify(c.sock, desired, c)
                    c.mask = desired
                except (KeyError, ValueError, OSError):
                    self._close_conn(sel, c)
                    continue
            if throttled and pending:
                timeout = min(timeout, max(0.0, c.next_t - now))
        return timeout

    def _accept_ready(self, sel, listener) -> bool:
        """Drain the accept backlog; False retires the listener."""
        while True:
            try:
                sock, _ = listener.accept()
            except BlockingIOError:
                return True
            except OSError:
                # drain()/stop() shut the listener down
                try:
                    sel.unregister(listener)
                    listener.close()
                except (KeyError, ValueError, OSError):
                    pass
                return False
            sock.setblocking(False)
            try:
                # hot RPC path: a multi-segment reply must not sit in
                # the Nagle buffer waiting on the peer's delayed ACK
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass  # non-TCP transports (tests) have no such knob
            c = _Conn(sock)
            with self._conns_lock:
                self._conns.add(c)
            try:
                sel.register(sock, selectors.EVENT_READ, c)
                c.mask = selectors.EVENT_READ
            except (ValueError, OSError):
                self._close_conn(sel, c)

    def _read_ready(self, sel, c: _Conn) -> None:
        try:
            data = c.sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close_conn(sel, c)
            return
        if not data:
            self._close_conn(sel, c)
            return
        try:
            msgs = c.decoder.feed(data)
        except ValueError:
            self._close_conn(sel, c)  # framing garbage: cut the peer off
            return
        for msg in msgs:
            if trace.now_s() < self._replica_down_until:
                self._close_conn(sel, c)  # replica_down: drop, no reply
                return
            if self._dispatch(c, msg) == "drop":
                self._close_conn(sel, c)
                return

    def _flush(self, c: _Conn, budget: int | None = None) -> bool:
        """Write queued frames to the socket until it would block, the
        queue empties, or the byte budget (throttled conns) runs out.
        False means the peer is gone and the conn must be closed.
        ``tx`` is held across the whole drain so the loop thread and a
        worker's direct send can never interleave bytes on the wire."""
        with c.tx:
            try:
                while True:
                    with c.lock:
                        if c.closed:
                            return False
                        if not c.wq:
                            return True
                        head = c.wq[0]
                        off = c.head_off
                        c.sending = True
                    # memoryview slices: resuming a partially-sent frame
                    # (and budget-capping a throttled one) must not copy
                    # the frame tail on every send() round (ISSUE 16)
                    chunk = memoryview(head)[off:]
                    if budget is not None:
                        if budget <= 0:
                            return True
                        chunk = chunk[:budget]
                    try:
                        n = c.sock.send(chunk)
                    except (BlockingIOError, InterruptedError):
                        return True
                    except OSError:
                        return False
                    if budget is not None:
                        budget -= n
                    with c.lock:
                        if c.closed:
                            return False
                        c.head_off += n
                        c.wq_bytes -= n
                        if c.head_off >= len(head):
                            c.wq.popleft()
                            c.head_off = 0
            finally:
                with c.lock:
                    c.sending = False

    def _tick_throttled(self, sel) -> None:
        """svc_slow_frame drain: each throttled connection gets at most
        ``throttle_bps`` bytes per ``_TICK_S``, on its own clock."""
        now = time.monotonic()
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            if c.closed or c.throttle_bps <= 0 or now < c.next_t:
                continue
            if not c.pending():
                continue
            c.next_t = now + _TICK_S
            if not self._flush(c, budget=max(1, int(c.throttle_bps))):
                self._close_conn(sel, c)

    def _close_conn(self, sel, c: _Conn) -> None:
        with self._conns_lock:
            self._conns.discard(c)
        with c.lock:
            c.closed = True
            c.wq.clear()
            c.wq_bytes = 0
            c.head_off = 0
        try:
            sel.unregister(c.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            c.sock.close()
        except OSError:
            pass

    def _reply(self, c: _Conn, payload: dict, front: bool = False,
               cols: dict | None = None) -> None:
        """Enqueue one encoded reply frame on the connection's bounded
        write queue and wake the loop. ``front=True`` (inline ops) jumps
        ahead of queued query replies — but never into the middle of a
        partially-sent frame. Called from worker threads and from the
        loop itself; a closed conn swallows the reply (the outcome is
        already counted), and overflowing the queue kills the slow
        consumer rather than growing without bound.

        When the queue held nothing before this frame and the conn is
        unthrottled, the calling thread flushes the frame to the socket
        directly instead of waking the loop — on a busy box the
        wake-byte + selector-thread context switch costs more than the
        reply itself, and an idle-queue conn has no in-flight partial
        frame a direct send could interleave with (``tx`` guarantees
        it even against a racing loop flush).

        ``cols`` ships the payload as a v2 columnar frame (ISSUE 16) —
        callers pass it only on connections that negotiated v2."""
        frame = encode_msg_v2(payload, cols) if cols else encode_msg(payload)
        overflow = False
        direct = False
        queued = 0
        with c.lock:
            if c.closed or c.kill:
                return
            if c.wq_bytes + len(frame) > self.settings.write_queue_bytes:
                c.kill = True
                overflow = True
                queued = c.wq_bytes
            else:
                if front:
                    busy_head = (c.head_off > 0 or c.sending) and c.wq
                    c.wq.insert(1 if busy_head else 0, frame)
                else:
                    c.wq.append(frame)
                c.wq_bytes += len(frame)
                direct = (len(c.wq) == 1 and c.head_off == 0
                          and c.throttle_bps <= 0)
        if overflow:
            self._bump("slow_consumer_closed")
            self.metrics.event("service_slow_consumer", quietable=True,
                               queued_bytes=queued,
                               limit=self.settings.write_queue_bytes)
            self._wake()
            return
        if direct:
            if not self._flush(c):
                with c.lock:
                    c.kill = True  # peer gone; the loop reaps it
            elif not c.pending():
                return  # fully on the wire: the loop has nothing to do
        self._wake()

    def _dispatch(self, conn: _Conn, msg: dict) -> str | None:
        mtype = msg.get("type")
        rid = msg.get("id")
        idx = self.index  # one snapshot per message, even for health
        if mtype == "health":
            # answered inline by the event loop, front-inserted AHEAD of
            # queued query replies: health must stay observable under
            # full-queue shed pressure and a dead backend alike
            hot, cold = self._lane_depths()
            self._reply(conn, {
                "type": "health", "id": rid, "ok": True,
                "status": "degraded" if self.cold.degraded else "ok",
                "covered_hi": idx.covered_hi,
                "total_primes": idx.total_primes,
                "queue_depth": hot + cold,
                "queue_depth_hot": hot,
                "queue_depth_cold": cold,
                "brownout": self.brownout(),
                "snapshot_age_s": round(
                    trace.now_s() - self._snapshot_ts, 3
                ),
                "refreshes": self._refreshes,
                "draining": self._draining,
                "range_lo": self.base,
                "proc": self.settings.proc_index,
                # health() is the store's cheap in-memory subset — safe
                # inline on the wire loop, unlike the blocking store ops
                "store": (self.store.health()
                          if self.store is not None else None),
                # cold-plane identity (ISSUE 18) — describe() is
                # lock-free, so inline on the wire loop is safe
                **self.cold.describe(),
            }, front=True)
            return None
        if mtype == "stats":
            self._reply(conn,
                        {"type": "stats", "id": rid, "ok": True,
                         "stats": self.stats()}, front=True)
            return None
        if mtype == "hello":
            # wire-version negotiation (ISSUE 16): intersect the peer's
            # advertised versions with ours, highest mutual wins, v1
            # JSON is the floor. Answered inline BEFORE any pipelined
            # query reply, so the client knows the encoding of
            # everything that follows. Decoding is capability-based
            # (frames are self-describing) — negotiation only governs
            # what each side sends.
            try:
                peer = {int(v) for v in (msg.get("wire") or ())
                        if not isinstance(v, bool)}
            except (TypeError, ValueError):
                peer = set()
            mine = set(SUPPORTED_WIRE) if self.settings.wire_v2 \
                else {WIRE_V1}
            mutual = peer & mine
            conn.wire_v = max(mutual) if mutual else WIRE_V1
            if conn.wire_v >= WIRE_V2:
                self._bump("wire_v2_conns")
            self._reply(conn, {"type": "hello", "id": rid, "ok": True,
                               "wire": conn.wire_v,
                               "versions": sorted(mine)}, front=True)
            return None
        if mtype == "shutdown":
            # rolling-restart control message: same path as SIGTERM
            self._reply(conn,
                        {"type": "reply", "id": rid, "ok": True,
                         "draining": True}, front=True)
            self.drain()
            return None
        if mtype == "metrics":
            # live telemetry plane (ISSUE 12): the full registry
            # snapshot, answered inline like health — the fleet poller
            # must see a wedged server's counters, not time out behind
            # its queue
            self._reply(conn, {
                "type": "metrics", "id": rid, "ok": True,
                "role": "service", "metrics": registry().snapshot(),
            }, front=True)
            return None
        if mtype == "debug":
            # flight-recorder freeze (ISSUE 13): answered inline by the
            # event loop like metrics, so a wedged worker pool still
            # dumps its black box (no disk write, no throttle)
            self._reply(conn, {
                "type": "debug", "id": rid, "ok": True, "role": "service",
                "bundle": (self.recorder.snapshot("manual")
                           if self.recorder is not None else None),
            }, front=True)
            return None
        if mtype == "profile":
            # continuous-profiler pull (ISSUE 20): collapsed-stack table,
            # inline from the event loop like debug — a wedged worker
            # pool still profiles. svc_prof_gap chaos drops the K-th
            # reply (puller times out, never sees a malformed frame) and
            # pauses the sampler one beat.
            self._prof_pulls += 1
            gap = bool(self.chaos.take_kinds(0, self._prof_pulls,
                                             PROFILE_KINDS))
            snap = (self.profiler.snapshot()
                    if self.profiler is not None else None)
            self.metrics.event(
                "profile_pulled", quietable=True, role="service",
                samples=(snap or {}).get("samples"),
                stacks=len((snap or {}).get("stacks") or ()), gap=gap,
            )
            if gap:
                self._bump("profile_gaps")
                if self.profiler is not None:
                    self.profiler.pause(1)
                return None
            self._bump("profile_pulls")
            self._reply(conn, {
                "type": "profile", "id": rid, "ok": True,
                "role": "service", "profile": snap,
            }, front=True)
            return None
        if mtype == "exemplars":
            # tail-sampled exemplar pull (ISSUE 19): the kept-exemplar
            # ring, inline from the event loop (in-memory only — the
            # rolling file is the sampler's own concern). ``ctx`` prefix
            # filter is how the router fetches the downstream exemplars
            # of one slow route.
            ctx_f = msg.get("ctx")
            n_f = msg.get("n")
            self._reply(conn, {
                "type": "exemplars", "id": rid, "ok": True,
                "role": "service",
                "exemplars": (self.exemplar.tail(
                    n=n_f if isinstance(n_f, int) else None,
                    ctx_prefix=ctx_f if isinstance(ctx_f, str) else None,
                ) if self.exemplar is not None else []),
            }, front=True)
            return None
        if mtype == "telemetry":
            # explicit ring flush: the router pulls this from every
            # replica when its trace closes, collecting whatever the
            # batched piggyback has not shipped yet. Echoes the clock
            # stamps so the flush itself feeds the caller's aligner.
            reply: dict[str, Any] = {"type": "telemetry", "id": rid,
                                     "ok": True}
            if msg.get("t_send") is not None:
                reply["t_recv"] = round(trace.now_s(), 6)
            if self._telemetry_on:
                events, dropped = trace.drain_events()
                reply["telemetry"] = {"events": events, "dropped": dropped}
                self._bump("telemetry_replies")
            if msg.get("t_send") is not None:
                reply["t_sent"] = round(trace.now_s(), 6)
            self._reply(conn, reply, front=True)
            return None
        if mtype == "chaos":
            if not self.settings.wire_chaos:
                # refusal is typed AND evented: a production server must
                # record who tried to fault-inject it over the wire
                self.metrics.event("service_chaos_refused",
                                   spec=str(msg.get("spec", "")))
                self._reply(conn, {
                    "type": "reply", "id": rid, "ok": False,
                    "error": "bad_request",
                    "detail": "wire chaos injection is disabled on this "
                              "server (start it with --allow-chaos)",
                }, front=True)
                return None
            try:
                n = self.inject_chaos(str(msg.get("spec", "")))
            except ValueError as e:
                self._reply(conn,
                            {"type": "reply", "id": rid, "ok": False,
                             "error": "bad_request", "detail": str(e)},
                            front=True)
                return None
            self._reply(conn,
                        {"type": "reply", "id": rid, "ok": True,
                         "injected": n}, front=True)
            return None
        if mtype != "query":
            self._reply(conn,
                        {"type": "reply", "id": rid, "ok": False,
                         "error": "bad_request",
                         "detail": f"unknown message type {mtype!r}"})
            return None
        dl = msg.get("deadline_s")
        if dl is not None and (
            not isinstance(dl, (int, float)) or isinstance(dl, bool)
            or dl <= 0 or not math.isfinite(dl)
        ):
            # a malformed deadline is the CLIENT's bug: reply typed
            # bad_request instead of manufacturing an already-expired
            # deadline and calling it deadline_exceeded
            self._bump("bad_requests")
            self._reply(conn, {
                "type": "reply", "id": rid, "ok": False,
                "op": str(msg.get("op", "")), "error": "bad_request",
                "detail": f"deadline_s must be a positive number, "
                          f"got {dl!r}",
            })
            return None
        with self._seq_lock:
            self._seq += 1
            seq = self._seq
        directives = self.chaos.take_kinds(0, seq, SERVICE_REQUEST_KINDS)
        op = str(msg.get("op", ""))
        for d in directives:
            if d["kind"] == "replica_down":
                self._replica_down_until = max(
                    self._replica_down_until,
                    trace.now_s() + float(d["param"] or 0.0),
                )
                return "drop"  # dead replica: no reply, connection cut
            if d["kind"] == "svc_drain":
                self.drain()
            if d["kind"] == "svc_slow_frame":
                # from this request on, replies to THIS connection are
                # dribbled at param bytes per event-loop tick; other
                # connections must stay at full speed (gated by test)
                conn.throttle_bps = max(1.0, float(d["param"] or 1.0))
                self.metrics.event("service_slow_frame", quietable=True,
                                   bytes_per_tick=conn.throttle_bps)
        if any(d["kind"] == "svc_shed" for d in directives):
            self._shed(conn, rid, op, forced=True, ctx=msg.get("ctx"))
            return None
        flood = next(
            (d for d in directives if d["kind"] == "svc_flood"), None
        )
        if flood is not None:
            # svc_flood:any@sK:<lane> — request K is refused as if the
            # named lane were at capacity: the deterministic injection of
            # the lane-shed surface (reply lane field, service_lane_shed
            # event, ReplicaSet failover) without a real 20-thread flood
            self._shed(conn, rid, op, forced=True,
                       lane=str(flood["param"] or "cold"),
                       chaos_kind="svc_flood", ctx=msg.get("ctx"))
            return None
        if self._draining:
            hot, cold = self._lane_depths()
            self._bump("draining_replies")
            self.metrics.event("service_shed", quietable=True, op=op,
                               queue_depth=hot + cold,
                               reason="draining")
            self._reply(conn, {
                "type": "reply", "id": rid, "ok": False, "op": op,
                "error": "draining",
                "detail": "server is draining (rolling restart); retry "
                          "on another replica",
            })
            return None
        lane = self._classify(msg, idx)
        item = (msg, rid if rid is not None else seq, trace.now_s(),
                directives, idx, conn, lane, False)
        with self._inflight_lock:
            self._inflight_n += 1
        if not self._lane_put(lane, item):
            with self._inflight_lock:
                self._inflight_n -= 1
            self._shed(conn, rid, op, forced=False, lane=lane,
                       ctx=msg.get("ctx"))
            return None
        self._bump(f"{lane}_admitted")
        return None

    def _shed(self, conn: _Conn, rid, op: str, forced: bool,
              lane: str | None = None, chaos_kind: str = "svc_shed",
              ctx: Any = None) -> None:
        hot, cold = self._lane_depths()
        depth = hot + cold
        self._bump("shed")
        self.metrics.event("service_shed", quietable=True, op=op,
                           queue_depth=depth)
        if lane is not None:
            self._bump(f"lane_shed_{lane}")
            self.metrics.event(
                "service_lane_shed", quietable=True, op=op, lane=lane,
                queue_depth=hot if lane == "hot" else cold,
            )
        if forced and lane is not None:
            detail = (f"shed by injected {chaos_kind} fault "
                      f"({lane} lane at capacity)")
        elif forced:
            detail = "shed by injected svc_shed fault"
        else:
            with self._lane_cond:
                limit = self._lane_limit_locked(lane)
            d = hot if lane == "hot" else cold
            detail = f"admission queue full: {lane} lane ({d}/{limit})"
            if lane == "cold" and limit < self._cold_limit:
                detail += " [brownout: cold limit halved]"
        reply = {
            "type": "reply", "id": rid, "ok": False, "op": op,
            "error": "overloaded", "detail": detail,
        }
        if lane is not None:
            reply["lane"] = lane
        # a shed never ran, so there is no span tree — but the typed
        # outcome is still exemplar-kept (ISSUE 19), so the file records
        # every refused request alongside the slow ones
        if self.exemplar is not None:
            self._bump("exemplars_seen")
            reason = self.exemplar.decide("overloaded", 0.0)
            if reason is not None:
                self._bump("exemplars_kept")
                self.exemplar.keep({
                    "ctx": ctx if isinstance(ctx, str) and ctx else None,
                    "op": op, "outcome": "overloaded", "ms": 0.0,
                    "lane": lane, "reason": reason, "spans": [],
                })
        self._reply(conn, reply)

    # --- request handling ------------------------------------------------

    def _classify(self, msg: dict, idx: SieveIndex) -> str:
        """Lane classification at enqueue (ISSUE 10): **hot** iff the
        query is fully answerable from SieveIndex + the caches — hi
        within covered_hi (every slice is index-materializable), or
        every grid chunk past covered already sitting in the cold
        cache. Anything that may need a backend dispatch is **cold**.
        Malformed queries classify hot: a typed bad_request is cheap
        and must never queue behind a cold flood."""
        op = msg.get("op")
        try:
            if op == "pi":
                return self._lane_for_prefixes([int(msg["x"]) + 1], idx)
            if op == "is_prime":
                x = int(msg["x"])
                return self._lane_for_prefixes([x, x + 1], idx)
            if op == "count":
                lo, hi = int(msg["lo"]), int(msg["hi"])
                if hi < lo or hi > MAX_HI:
                    return "hot"  # typed bad_request
                if str(msg.get("kind", "primes")) == "primes":
                    return self._lane_for_prefixes([lo, hi], idx)
                # pair kinds enumerate: hot only within the index
                return "hot" if hi <= idx.covered_hi else "cold"
            if op == "nth_prime":
                return ("hot" if int(msg["k"]) <= idx.total_primes
                        else "cold")
            if op == "primes":
                lo, hi = int(msg["lo"]), int(msg["hi"])
                if hi < lo or hi > MAX_HI:
                    return "hot"
                return "hot" if hi <= idx.covered_hi else "cold"
            if op == "batch":
                if "b_op" in msg:
                    # columnar batch (ISSUE 16): one vectorized bound
                    # check instead of a member loop. The max over both
                    # argument columns (+1 for the pi/is_prime prefix)
                    # over-approximates every needed prefix; anything
                    # malformed classifies hot (typed bad_request).
                    b_a, b_b = msg.get("b_a"), msg.get("b_b")
                    try:
                        if b_a.size == 0:
                            return "hot"
                        top = max(int(b_a.max()) + 1, int(b_b.max()))
                    except (AttributeError, TypeError, ValueError):
                        return "hot"
                    return self._lane_for_prefixes([top], idx)
                items = msg.get("items")
                if (not isinstance(items, list) or not items
                        or len(items) > self.settings.batch_queries):
                    return "hot"  # whole-batch typed bad_request
                vs: list[int] = []
                for m in items:
                    if not isinstance(m, dict):
                        continue  # per-member typed bad_request, cheap
                    try:
                        mop = m.get("op")
                        if mop == "pi":
                            vs.append(int(m["x"]) + 1)
                        elif mop == "is_prime":
                            x = int(m["x"])
                            vs.extend((x, x + 1))
                        elif mop == "count":
                            vs.extend((int(m["lo"]), int(m["hi"])))
                    except (KeyError, TypeError, ValueError):
                        continue
                return self._lane_for_prefixes(vs, idx) if vs else "hot"
        except (KeyError, TypeError, ValueError):
            return "hot"  # malformed → typed bad_request, cheap
        return "hot"  # unknown op → typed bad_request

    def _lane_for_prefixes(self, vs: list[int], idx: SieveIndex) -> str:
        keys: set[tuple[int, int]] = set()
        for v in vs:
            if v > MAX_HI:
                return "hot"  # typed bad_request
            keys.update(self._grid_chunks(min(v, idx.covered_hi), v))
        if not keys:
            return "hot"
        if len(keys) > 32:
            return "cold"  # too many chunks to probe the cache for
        with self._cold_lock:
            return ("hot" if all(k in self._cold_cache for k in keys)
                    else "cold")

    def _grid_chunks(self, covered: int, v: int) -> list[tuple[int, int]]:
        """The cold chunk list [covered, v) on the fixed grid — shared
        by classification and _count_upto so they can never disagree."""
        chunks: list[tuple[int, int]] = []
        a = covered
        while a < v:
            b = min(_grid_next(a, self.settings.cold_chunk), v)
            chunks.append((a, b))
            a = b
        return chunks

    def _worker_loop(self, dedicated: bool = False) -> None:
        while True:
            item = self._next_item(dedicated)
            if item is None:
                return
            try:
                self._handle(*item)
            except ChaosCrash:
                raise  # svc_crash drill: this worker thread must die
            except Exception:
                pass  # _handle replies "internal" itself; never die

    def _requeue_cold(self, msg, rid, enq_t, idx, conn) -> bool:
        """Demotion (ISSUE 10): re-enqueue a misclassified hot request on
        the cold lane. The original enq_t rides along, so its deadline
        keeps draining and cold-lane aging sees its true wait."""
        item = (msg, rid, enq_t, (), idx, conn, "cold", True)
        return self._lane_put("cold", item)

    def _handle(self, msg, rid, enq_t, directives, idx,
                conn: _Conn, lane: str = "cold",
                demoted: bool = False) -> None:
        # ``idx`` is the snapshot captured at admission: the whole request
        # runs on it even if the follower swaps self.index mid-flight
        op = str(msg.get("op", ""))
        # trace ctx (ISSUE 12): echo the caller's context into every span
        # this request produces, so the router/report can correlate them
        tctx = msg.get("ctx")
        tkw = {"ctx": tctx} if isinstance(tctx, str) and tctx else {}
        t_pop = trace.now_s()
        trace.add_span("query.queue_wait", enq_t, t_pop - enq_t, op=op,
                       lane=lane, **tkw)
        registry().histogram(f"service.queue_wait_ms.{lane}").observe(
            (t_pop - enq_t) * 1000.0
        )
        deadline = enq_t + float(
            msg.get("deadline_s") or self.settings.default_deadline_s
        )
        ctx = QueryCtx()
        ctx.lane = lane

        def check() -> None:
            if trace.now_s() > deadline:
                raise DeadlineExceeded(ctx.answered_hi, ctx.count_so_far)

        ctx.check = check
        if not demoted:  # a demoted re-run is the SAME request
            self._bump("requests")
        outcome = "ok"
        reply: dict = {"type": "reply", "id": rid, "ok": True, "op": op}
        try:
            for d in directives:
                if d["kind"] == "svc_stall":
                    time.sleep(float(d["param"] or 0.0))
                elif d["kind"] == "backend_down":
                    self.cold.force_down(float(d["param"] or 0.0),
                                         "chaos backend_down")
                elif d["kind"] == "svc_crash":
                    raise ChaosCrash(
                        f"chaos svc_crash: worker killed mid-{op or 'query'}"
                    )
            check()
            reply["value"] = self._execute(op, msg, ctx, deadline, idx)
        except _Demoted as e:
            if self._requeue_cold(msg, rid, enq_t, idx, conn):
                self._bump("demoted")
                self.metrics.event("service_demoted", quietable=True,
                                   op=op, chunks=e.chunks)
                # no reply, no inflight decrement: the cold re-run of
                # this same request owns both now
                return
            # cold lane refused the demotion: typed lane shed
            outcome = "overloaded"
            _h, c = self._lane_depths()
            self._bump("shed")
            self._bump("lane_shed_cold")
            self.metrics.event("service_lane_shed", quietable=True, op=op,
                               lane="cold", queue_depth=c)
            reply = {
                "type": "reply", "id": rid, "ok": False, "op": op,
                "error": "overloaded", "lane": "cold",
                "detail": "cold lane full while demoting a misclassified "
                          "hot query; retry",
                "partial": None,
            }
        except ChaosCrash:
            # svc_crash drill: this request will never reply, so settle
            # its drain accounting here, then let the exception escape
            # both catch-all nets — the worker thread must genuinely die
            # so threading.excepthook (the recorder's crash hook) fires
            with self._inflight_lock:
                self._inflight_n -= 1
            self._maybe_drained()
            raise
        except tuple(_ERROR_KIND) as e:
            outcome = _ERROR_KIND[type(e)]
            reply = {
                "type": "reply", "id": rid, "ok": False, "op": op,
                "error": outcome, "detail": str(e),
                "partial": self._partial(op, e),
            }
        except Exception as e:  # noqa: BLE001 — server must not die
            outcome = "internal"
            reply = {
                "type": "reply", "id": rid, "ok": False, "op": op,
                "error": "internal", "detail": f"{type(e).__name__}: {e}",
                "partial": None,
            }
        t_end = trace.now_s()
        source = ctx.source()
        reply.setdefault("source", source)
        reply["elapsed_ms"] = round((t_end - enq_t) * 1000, 3)
        trace.add_span("rpc.query", enq_t, t_end - enq_t, op=op,
                       outcome=outcome, source=source, lane=lane, **tkw)
        self._observe_slo(op, reply["elapsed_ms"])
        # tail-sampled exemplar (ISSUE 19): now that the outcome is
        # known, decide whether this request's span tree is kept. The
        # rpc.query span above is already in the tracer's exemplar ring.
        if self.exemplar is not None:
            self._bump("exemplars_seen")
            reason = self.exemplar.decide(
                outcome, reply["elapsed_ms"], flagged=demoted,
            )
            if reason is not None:
                self._bump("exemplars_kept")
                self.exemplar.keep({
                    "ctx": tctx if tkw else None,
                    "op": op,
                    "outcome": outcome,
                    "ms": reply["elapsed_ms"],
                    "lane": lane,
                    "reason": reason,
                    "spans": (trace.exemplar_collect(tctx)
                              if tkw else []),
                })
        # counters/events before the reply: a stats call racing the
        # reply must already see this request accounted for
        if outcome == "ok" and not ctx.cold and not ctx.materialized:
            self._bump("index_hits")
        elif outcome == "deadline_exceeded":
            self._bump("deadline_exceeded")
        elif outcome == "degraded":
            self._bump("degraded_replies")
        elif outcome == "bad_request":
            self._bump("bad_requests")
        elif outcome == "internal":
            self._bump("internal_errors")
        self.metrics.event(
            "service_request", quietable=True, op=op, outcome=outcome,
            source=source, ms=reply["elapsed_ms"],
        )
        # telemetry piggyback (ISSUE 12): echo receive/send timestamps
        # for the caller's clock aligner, and — when asked and armed —
        # drain the bounded span ring onto this reply (the rpc.query
        # span above is already in it). Batched: only ship once
        # telemetry_batch events are pending, so the hot path is not
        # paying a serialize per reply; the ``telemetry`` wire op
        # flushes the remainder when the caller's trace closes.
        # svc_trace_drop ships an explicit null payload: telemetry
        # lost (the pending ring is discarded, not deferred), query
        # result untouched.
        if msg.get("t_send") is not None:
            reply["t_recv"] = round(enq_t, 6)
        if msg.get("telemetry"):
            if any(d["kind"] == "svc_trace_drop" for d in directives):
                trace.drain_events()
                reply["telemetry"] = None
                self._bump("trace_drops")
                self.metrics.event("service_trace_drop", quietable=True,
                                   op=op)
            elif (self._telemetry_on and trace.pending_events()
                    >= self.settings.telemetry_batch):
                events, dropped = trace.drain_events()
                reply["telemetry"] = {"events": events, "dropped": dropped}
                self._bump("telemetry_replies")
        if msg.get("t_send") is not None:
            reply["t_sent"] = round(trace.now_s(), 6)
        # reply finalization (ISSUE 16): array-shaped values become v2
        # columns on a negotiated connection, or plain JSON lists on v1
        # — the op handlers above never branch on the wire version
        cols = None
        val = reply.get("value")
        if isinstance(val, BatchOutcomes):
            if conn.wire_v >= WIRE_V2:
                del reply["value"]
                extra, cols = val.wire()
                reply.update(extra)
            else:
                reply["value"] = val.to_items()
        elif isinstance(val, np.ndarray):
            if conn.wire_v >= WIRE_V2:
                del reply["value"]
                extra, cols = primes_to_cols(val, self.config.packing,
                                             int(msg.get("lo", 0)),
                                             int(msg.get("hi", 0)))
                reply.update(extra)
            else:
                reply["value"] = val.tolist()
        try:
            self._reply(conn, reply, cols=cols)
        finally:
            # drain accounting: this admitted query is now answered
            with self._inflight_lock:
                self._inflight_n -= 1
            self._maybe_drained()

    @staticmethod
    def _partial(op: str, e: Exception) -> dict | None:
        if not isinstance(e, DeadlineExceeded):
            return None
        if op == "pi":
            return {"answered_hi": e.answered_hi, "pi_so_far": e.count_so_far}
        if op == "nth_prime":
            return {"searched_hi": e.answered_hi,
                    "count_so_far": e.count_so_far}
        return {"answered_hi": e.answered_hi, "count_so_far": e.count_so_far}

    # --- ops -------------------------------------------------------------

    def _execute(self, op: str, msg: dict, ctx: QueryCtx, deadline: float,
                 idx: SieveIndex):
        if op == "pi":
            if self.base > 2:
                # a shard-local prefix count is NOT pi: refusing here is
                # what lets the router compose exact global answers
                raise BadRequest(
                    f"pi is a global-prefix op; this server serves "
                    f"[{self.base}, ...) — use count(lo, hi) or query "
                    "the router"
                )
            x = _req_int(msg, "x")
            if x < 0 or x + 1 > MAX_HI:
                raise BadRequest(f"pi({x}): x must be in [0, {MAX_HI})")
            return self._count_upto(x + 1, ctx, deadline, idx)
        if op == "is_prime":
            x = _req_int(msg, "x")
            if x + 1 > MAX_HI:
                raise BadRequest(f"is_prime({x}): x must be < {MAX_HI}")
            if x < 2:
                return False
            self._check_base(op, x)
            lo_c = self._count_upto(x, ctx, deadline, idx)
            return self._count_upto(x + 1, ctx, deadline, idx) - lo_c > 0
        if op == "count":
            lo, hi = _req_int(msg, "lo"), _req_int(msg, "hi")
            if hi > lo:
                self._check_base(op, lo)
            kind = str(msg.get("kind", "primes"))
            return self._count(lo, hi, kind, ctx, deadline, idx)
        if op == "nth_prime":
            return self._nth_prime(_req_int(msg, "k"), ctx, deadline, idx)
        if op == "primes":
            lo, hi = _req_int(msg, "lo"), _req_int(msg, "hi")
            if hi > lo:
                self._check_base(op, lo)
            return self._primes(lo, hi, ctx, deadline, idx)
        if op == "batch":
            if "b_op" in msg:
                return self._execute_batch_cols(msg, ctx, deadline, idx)
            return self._execute_batch(msg, ctx, deadline, idx)
        raise BadRequest(
            f"unknown op {op!r} (one of pi, is_prime, count, nth_prime, "
            "primes, batch)"
        )

    def _execute_batch_cols(self, msg: dict, ctx: QueryCtx, deadline: float,
                            idx: SieveIndex) -> BatchOutcomes:
        """Columnar batch fast path (ISSUE 16): validate and answer M
        members with pure array ops — zero per-member Python objects.

        The request arrives as ``b_op``/``b_a``/``b_b`` columns (see
        :func:`sieve.rpc.batch_items_to_cols`). When every member is
        well-formed and every needed prefix is inside the index, the
        whole batch is: dedup -> one ``count_upto_batch`` row -> three
        masked gathers. ANY deviation — unknown opcode, bound
        violation, shard-base issue, a cold value — rebuilds the member
        dicts and delegates to :meth:`_execute_batch`, which owns the
        typed per-member outcome semantics (and ``_Demoted``); the fast
        path never re-implements an error message."""
        b_op, b_a, b_b = msg.get("b_op"), msg.get("b_a"), msg.get("b_b")
        if (not isinstance(b_op, np.ndarray) or not isinstance(b_a, np.ndarray)
                or not isinstance(b_b, np.ndarray)
                or not (b_op.size == b_a.size == b_b.size)):
            raise BadRequest("batch: malformed column payload")
        m = int(b_op.size)
        if m == 0:
            raise BadRequest("batch: items must be a non-empty list")
        if m > self.settings.batch_queries:
            raise BadRequest(
                f"batch: {m} members exceed "
                f"batch_queries={self.settings.batch_queries}"
            )
        ops = b_op.astype(np.int64)
        a = b_a.astype(np.int64)
        b = b_b.astype(np.int64)
        pi_m = ops == 0
        ip_m = ops == 1
        ct_m = ops == 2
        fast = bool(
            (pi_m | ip_m | ct_m).all()
            and not (pi_m.any() and self.base > 2)
            and not (a[pi_m] < 0).any()
            # spelled >= MAX_HI (not +1 > MAX_HI): x+1 on an int64 max
            # would wrap negative and sneak past the guard
            and not (a[pi_m | ip_m] >= MAX_HI).any()
            and not (b[ct_m] > MAX_HI).any()
            and not (b[ct_m] < a[ct_m]).any()
        )
        if fast and self.base > 2:
            # shard server: scalar paths typed-reject members below the
            # shard base (is_prime keeps the x<2 -> False carve-out)
            if ((ip_m & (a >= 2) & (a < self.base))
                    | (ct_m & (b > a) & (a < self.base))).any():
                fast = False
        if fast:
            needed = np.concatenate(
                (a[pi_m] + 1, a[ip_m], a[ip_m] + 1, a[ct_m], b[ct_m])
            )
            if needed.size and int(needed.max()) > idx.covered_hi:
                fast = False  # a cold prefix: the member loop owns it
        if not fast:
            sub = dict(msg)
            sub["items"] = batch_cols_to_items(b_op, b_a, b_b)
            return BatchOutcomes.from_items(
                self._execute_batch(sub, ctx, deadline, idx)
            )
        self._bump("batch_requests")
        self._bump("batch_members", m)
        uniq = np.unique(needed)
        resolved = np.zeros(uniq.size, dtype=np.int64)
        hot = uniq > self.base  # <= base resolves to 0 by definition
        if hot.any():
            resolved[hot] = idx.count_upto_batch(uniq[hot], ctx)

        def pref(vs: np.ndarray) -> np.ndarray:
            return resolved[np.searchsorted(uniq, vs)]

        val = np.zeros(m, dtype=np.int64)
        val[pi_m] = pref(a[pi_m] + 1)
        val[ip_m] = pref(a[ip_m] + 1) - pref(a[ip_m]) > 0
        val[ct_m] = pref(b[ct_m]) - pref(a[ct_m])
        return BatchOutcomes(np.ones(m, dtype=np.uint8), val, {}, b_op)

    def _execute_batch(self, msg: dict, ctx: QueryCtx, deadline: float,
                       idx: SieveIndex) -> list[dict]:
        """Vectorized batch op (ISSUE 14): M prefix/interval/is_prime
        members answered as per-member typed outcomes.

        Every member decomposes into prefix counts P(v) = primes in
        [base, v): pi(x) = P(x+1), count(lo,hi) = P(hi) - P(lo),
        is_prime(x) = P(x+1) - P(x) > 0. The distinct v's are deduped,
        every hot one (≤ covered_hi) is answered by ONE
        ``np.searchsorted`` row over the index prefix
        (:meth:`SieveIndex.count_upto_batch`), and cold ones walk the
        existing scalar path — ascending, so the ColdBatcher coalesces
        their chunk flights — each catching its typed fault
        individually. A member whose values all resolved replies
        ``{"ok": True, "value": ...}``; one touching a faulted value
        replies ``{"ok": False, "error": <kind>, ...}`` (deadline
        members carry the prefix partial). Malformed members are typed
        per-member; a malformed items container or an oversized batch
        is a whole-batch bad_request. ``_Demoted`` propagates whole-
        batch so the standard demotion path re-runs it on the cold
        lane."""
        items = msg.get("items")
        if not isinstance(items, list) or not items:
            raise BadRequest("batch: items must be a non-empty list")
        if len(items) > self.settings.batch_queries:
            raise BadRequest(
                f"batch: {len(items)} members exceed "
                f"batch_queries={self.settings.batch_queries}"
            )
        self._bump("batch_requests")
        self._bump("batch_members", len(items))
        # plan each member: ("err", outcome) | (mop, needed_vals, finish)
        plans: list[tuple] = []
        needed: set[int] = set()
        for m in items:
            mop = str(m.get("op", "")) if isinstance(m, dict) else ""
            try:
                if not isinstance(m, dict):
                    raise BadRequest("batch member must be an object")
                if mop == "pi":
                    if self.base > 2:
                        raise BadRequest(
                            f"pi is a global-prefix op; this server "
                            f"serves [{self.base}, ...) — use "
                            "count(lo, hi) or query the router"
                        )
                    x = _req_int(m, "x")
                    if x < 0 or x + 1 > MAX_HI:
                        raise BadRequest(
                            f"pi({x}): x must be in [0, {MAX_HI})"
                        )
                    plans.append((mop, (x + 1,), lambda p: p[0]))
                elif mop == "is_prime":
                    x = _req_int(m, "x")
                    if x + 1 > MAX_HI:
                        raise BadRequest(
                            f"is_prime({x}): x must be < {MAX_HI}"
                        )
                    if x < 2:
                        plans.append((mop, (), lambda p: False))
                        continue
                    self._check_base(mop, x)
                    plans.append(
                        (mop, (x, x + 1), lambda p: p[1] - p[0] > 0)
                    )
                elif mop == "count":
                    lo, hi = _req_int(m, "lo"), _req_int(m, "hi")
                    if hi > MAX_HI:
                        raise BadRequest(f"count: hi={hi} exceeds {MAX_HI}")
                    if hi < lo:
                        raise BadRequest(f"count: hi={hi} < lo={lo}")
                    if str(m.get("kind", "primes")) != "primes":
                        raise BadRequest(
                            "batch count members support kind=primes only"
                        )
                    if hi > lo:
                        self._check_base(mop, lo)
                    plans.append((mop, (lo, hi), lambda p: p[1] - p[0]))
                else:
                    raise BadRequest(
                        f"unknown batch member op {mop!r} "
                        "(one of pi, is_prime, count)"
                    )
            except BadRequest as e:
                plans.append(("err", {
                    "ok": False, "op": mop, "error": "bad_request",
                    "detail": str(e), "partial": None,
                }))
                continue
            needed.update(plans[-1][1])
        # resolve the deduped prefix values: one vectorized gather for
        # the hot set, then the cold tail ascending
        res: dict[int, int] = {}
        faults: dict[int, dict] = {}
        hot = sorted(v for v in needed
                     if self.base < v <= idx.covered_hi)
        for v in needed:
            if v <= self.base:
                res[v] = 0
        if hot:
            counts = idx.count_upto_batch(hot, ctx)
            for v, c in zip(hot, counts):
                res[v] = int(c)
        for v in sorted(v for v in needed if v > idx.covered_hi):
            try:
                res[v] = self._count_upto(v, ctx, deadline, idx)
            except _Demoted:
                raise  # whole batch re-runs on the cold lane
            except tuple(_ERROR_KIND) as e:
                fault = {"error": _ERROR_KIND[type(e)], "detail": str(e),
                         "partial": None}
                if isinstance(e, DeadlineExceeded):
                    fault["partial"] = {"answered_hi": e.answered_hi,
                                       "count_so_far": e.count_so_far}
                faults[v] = fault
        out: list[dict] = []
        for plan in plans:
            if plan[0] == "err":
                out.append(plan[1])
                continue
            mop, vals, finish = plan
            bad = next((v for v in vals if v in faults), None)
            if bad is not None:
                out.append({"ok": False, "op": mop, **faults[bad]})
            else:
                out.append({"ok": True, "op": mop,
                            "value": finish([res[v] for v in vals])})
        return out

    def _check_base(self, op: str, lo: int) -> None:
        """Range-sharded servers reject queries below their shard."""
        if self.base > 2 and lo < self.base:
            raise BadRequest(
                f"{op}: lo={lo} below this server's range "
                f"[{self.base}, ...) (range_lo={self.base})"
            )

    def _count_upto(self, v: int, ctx: QueryCtx, deadline: float,
                    idx: SieveIndex) -> int:
        """Primes in [base, v): index prefix + cold chunks past covered_hi
        (base is 2 on a whole-range server, range_lo on a shard).

        The WHOLE cold chunk list is computed up front and submitted to
        the batcher in one go (ISSUE 9) — a request spanning K chunks
        registers all K flights before the first wait, so one queue
        drain sees them together and one backend dispatch answers them."""
        if v <= self.base:
            return 0
        covered = min(v, idx.covered_hi)
        total = idx.count_upto(covered, ctx)
        if covered >= v:
            return total
        chunks = self._grid_chunks(covered, v)
        return total + self._cold_counts(chunks, ctx, deadline, base=total)

    def _count(self, lo: int, hi: int, kind: str,
               ctx: QueryCtx, deadline: float, idx: SieveIndex) -> int:
        if hi > MAX_HI:
            raise BadRequest(f"count: hi={hi} exceeds {MAX_HI}")
        if hi < lo:
            raise BadRequest(f"count: hi={hi} < lo={lo}")
        if kind == "primes":
            c_lo = self._count_upto(lo, ctx, deadline, idx)
            return self._count_upto(hi, ctx, deadline, idx) - c_lo
        if kind in ("twins", "cousins"):
            gap = 2 if kind == "twins" else 4
            if hi - lo > self.settings.max_pair_span:
                raise BadRequest(
                    f"count kind={kind}: span {hi - lo} exceeds "
                    f"{self.settings.max_pair_span} (pair counts enumerate)"
                )
            a = self._collect_primes(lo, hi, ctx, deadline, cap=None,
                                     idx=idx)
            return _pairs(a, gap)
        raise BadRequest(
            f"count: unknown kind {kind!r} (primes, twins, cousins)"
        )

    def _nth_prime(self, k: int, ctx: QueryCtx, deadline: float,
                   idx: SieveIndex) -> int:
        if k < 1:
            raise BadRequest(f"nth_prime({k}): k must be >= 1")
        if k <= idx.total_primes:
            return idx.nth(k, ctx)
        # extend past the index: cold-count the fixed grid until the
        # containing chunk, then materialize just that chunk locally
        seen = idx.total_primes
        ctx.index = bool(idx.segments)
        ctx.count_so_far = max(ctx.count_so_far, seen)
        a = idx.covered_hi
        while True:
            ctx.tick()
            if a >= MAX_HI:
                raise BadRequest(
                    f"nth_prime({k}): search passed MAX_HI={MAX_HI} "
                    f"with only {seen} primes"
                )
            # chunk-at-a-time on purpose: the search extent is unknown,
            # so there is no chunk list to pre-submit (concurrent
            # nth_prime searches still batch with each other's chunks)
            b = min(_grid_next(a, self.settings.cold_chunk), MAX_HI)
            c = self._cold_counts([(a, b)], ctx, deadline, base=seen)
            if seen + c >= k:
                return self._nth_in_window(a, b, k - seen, ctx, idx)
            seen += c
            a = b
            ctx.answered_hi = max(ctx.answered_hi, a)
            ctx.count_so_far = max(ctx.count_so_far, seen)

    def _nth_in_window(self, lo: int, hi: int, r: int, ctx: QueryCtx,
                       idx: SieveIndex) -> int:
        """r-th prime (1-indexed) inside [lo, hi) — r is known to exist."""
        layout = idx.layout
        extras = [p for p in layout.extra_primes if lo <= p < hi]
        if r <= len(extras):
            return extras[r - 1]
        r -= len(extras)
        flags = idx.get_flags(lo, hi, ctx)
        pos = np.nonzero(flags)[0][r - 1]
        return int(layout.values_np(lo, np.array([pos]))[0])

    def _primes(self, lo: int, hi: int, ctx: QueryCtx,
                deadline: float, idx: SieveIndex) -> np.ndarray:
        if hi > MAX_HI:
            raise BadRequest(f"primes: hi={hi} exceeds {MAX_HI}")
        if hi < lo:
            raise BadRequest(f"primes: hi={hi} < lo={lo}")
        # stays an int64 array: a v2 connection ships it as raw bitset
        # words or a packed column, a v1 connection gets .tolist() at
        # reply-encode time — either way, no per-element work here
        return self._collect_primes(lo, hi, ctx, deadline,
                                    cap=self.settings.max_primes, idx=idx)

    def _collect_primes(self, lo: int, hi: int, ctx: QueryCtx,
                        deadline: float, cap: int | None,
                        idx: SieveIndex) -> np.ndarray:
        """Materialize primes in [lo, hi) through the enumerate seam,
        feeding hot slices from the index LRU (``flags_fn``) and marking
        the request cold when a slice falls past the covered range."""
        lo = max(lo, 2)
        if hi <= lo:
            return np.zeros(0, dtype=np.int64)
        last_slice = [lo]

        def flags_fn(slo: int, shi: int):
            last_slice[0] = shi
            f = idx.flags_for_slice(slo, shi, ctx)
            if f is None:
                ctx.cold = True
                self._bump("cold_computes")
            return f

        out: list[np.ndarray] = []
        count = 0
        try:
            gen = primes_in_range(self.config.packing, lo, hi,
                                  bounds=idx.bounds, flags_fn=flags_fn)
        except ValueError as e:
            raise BadRequest(str(e)) from None
        for arr in gen:
            out.append(arr)
            count += arr.size
            ctx.answered_hi = max(ctx.answered_hi, last_slice[0])
            ctx.count_so_far = max(ctx.count_so_far, count)
            if cap is not None and count > cap:
                raise BadRequest(
                    f"primes: result exceeds {cap} values at "
                    f"{last_slice[0]}; narrow the window or raise "
                    f"SIEVE_SVC_MAX_PRIMES"
                )
            ctx.tick()
        return (np.concatenate(out) if out
                else np.zeros(0, dtype=np.int64))

    # --- cold tier: single-flight registration + batched dispatch --------

    def _cold_counts(self, chunks: list[tuple[int, int]], ctx: QueryCtx,
                     deadline: float, base: int = 0) -> int:
        """Primes across ``chunks`` (ascending, disjoint, grid-aligned).

        Single-flight registration happens for ALL chunks under one lock
        pass — per chunk the request is either a cache hit, a follower
        on an existing flight, or the registering leader — then every
        leader key is submitted to the batcher at once and the request
        waits on its flights in ascending order, so typed
        ``deadline_exceeded`` partials report the same contiguous prefix
        the sequential path did. ``base`` is the count already answered
        below ``chunks[0]`` (keeps ``ctx.count_so_far`` exact)."""
        plan: list[tuple[tuple[int, int], Any, _Flight | None, bool]] = []
        submit: list[tuple[int, int]] = []
        with self._cold_lock:
            for key in chunks:
                res = self._cold_cache.get(key)
                if res is not None:
                    self._cold_cache.move_to_end(key)
                    plan.append((key, res, None, False))
                    continue
                flight = self._inflight.get(key)
                if flight is None:
                    flight = self._inflight[key] = _Flight()
                    submit.append(key)
                    plan.append((key, None, flight, False))
                else:
                    plan.append((key, None, flight, True))
        if submit:
            ctx.cold = True
            self.batcher.submit(submit)
        if ctx.lane == "hot" and any(res is None for _k, res, _f, _fl in plan):
            # misclassified hot query (ISSUE 10): the chunks are already
            # handed to the cold plane (leaders submitted above, flights
            # registered); demote instead of parking a hot worker on a
            # backend dispatch. The cold re-run waits as a follower —
            # its tier bumps happen then, so nothing double-counts.
            raise _Demoted(sum(1 for _k, res, _f, _fl in plan
                               if res is None))
        for key, res, _f, follower in plan:
            if res is not None:
                ctx.cold_cached = True
                self._bump("cold_cache_hits")
            elif follower:
                self._bump("coalesced")
                self.metrics.event("service_coalesced", quietable=True,
                                   op="count_range", lo=key[0], hi=key[1])
        total = 0
        for key, res, flight, _follower in plan:
            ctx.tick()
            if res is None:
                assert flight is not None
                if not flight.event.wait(
                    timeout=max(0.0, deadline - trace.now_s())
                ):
                    raise DeadlineExceeded(ctx.answered_hi, ctx.count_so_far)
                if flight.error is not None:
                    if isinstance(flight.error, Degraded):
                        raise Degraded(str(flight.error))
                    raise RuntimeError(
                        f"batched cold compute failed: {flight.error}"
                    ) from flight.error
                ctx.cold = True
                res = flight.result
                assert res is not None
            total += int(res.count)
            ctx.answered_hi = max(ctx.answered_hi, key[1])
            ctx.count_so_far = max(ctx.count_so_far, base + total)
        return total

    def _persist_results(self, results) -> int:
        """Ledger write-back (``--persist-cold``): one atomic checksummed
        flush per batch. Best-effort by design — a full disk must degrade
        durability, never exactness of the replies in flight."""
        if self._writer is None:
            return 0
        # never shrink: a chunk clipped at a query's v shares its seg_id
        # (COLD_SEG_BASE + lo) with the full grid chunk — recording the
        # clipped one over an already-persisted larger hi would shrink
        # ledger coverage and strand every entry chained past it
        keep = [r for r in results
                if r.hi > self._writer.recorded_hi(r.seg_id)]
        if not keep:
            return 0
        try:
            self._writer.record_many(keep)
        except Exception:  # noqa: BLE001 — persistence never fails queries
            registry().counter("service.persist_failed").inc()
            return 0
        # tier-1 store entries (ISSUE 18): boundary words, not just
        # counts — the restart-hot half of --persist-cold. Keyed on the
        # exact (lo, hi) chunk, so clipped chunks persist independently
        # of the grid chunk sharing their seg_id. Best-effort, like the
        # ledger write above. ALL results qualify (not just `keep`): a
        # clipped chunk is an exact fact even when the ledger already
        # covers a larger hi for its seg_id.
        if self.store is not None and self.store.writer:
            try:
                for r in results:
                    self.store.put_boundary(
                        r.lo, r.hi, r.count, r.first_word, r.last_word
                    )
            except Exception:  # noqa: BLE001
                registry().counter("service.persist_failed").inc()
        self._bump("cold_persisted", len(keep))
        return len(keep)

    def _store_cold_result(self, key: tuple[int, int]):
        """Rebuild a cold chunk's SegmentResult from a persisted tier-1+
        store entry (ISSUE 18 restart-hot), or None. Tier 0 can't
        qualify — counts alone lack the boundary words downstream merges
        read — and pair-counting configs recompute: the store header has
        no twin_count field, so a synthesized result could carry a wrong
        one."""
        if self.store is None or self.config.twins:
            return None
        ent = self.store.get_entry(*key)
        if ent is None or ent[0] < TIER_BOUNDARY:
            return None
        tier, count, fw, lw = ent
        lo, hi = key
        return SegmentResult(
            seg_id=COLD_SEG_BASE + lo, lo=lo, hi=hi, count=int(count),
            twin_count=0, first_word=int(fw), last_word=int(lw),
            nbits=get_layout(self.config.packing).nbits(lo, hi),
            elapsed_s=0.0,
        )


def _grid_next(a: int, chunk: int) -> int:
    """Next cold-chunk boundary strictly above ``a`` on the fixed grid —
    overlapping queries land on identical (lo, hi) keys and coalesce."""
    return (a // chunk + 1) * chunk


def _req_int(msg: dict, field: str) -> int:
    v = msg.get(field)
    if not isinstance(v, int) or isinstance(v, bool):
        raise BadRequest(f"field {field!r} must be an integer, got {v!r}")
    return v


def _pairs(primes: np.ndarray, gap: int) -> int:
    """Pairs (p, p+gap) with both members present in the sorted array."""
    if primes.size < 2:
        return 0
    idx = np.searchsorted(primes, primes + gap)
    ok = idx < primes.size
    return int(np.count_nonzero(primes[idx[ok]] == primes[ok] + gap))
