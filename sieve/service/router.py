"""Range-sharded router tier (ISSUE 11 tentpole).

:class:`SieveRouter` fronts N contiguous range shards — each its own
ledger + :class:`~sieve.service.client.ReplicaSet` — behind the exact
wire protocol the single server speaks (sieve/rpc.py framing, the same
query/health/stats/shutdown/chaos message types, the same typed error
kinds). Clients need zero changes: a :class:`ServiceClient` pointed at
the router cannot tell it is not one server, except that the served
range is the union of every shard's.

Routing semantics, per op:

* ``is_prime(x)`` / ``nth_prime(k)`` — point queries, routed to ONE
  shard: ``is_prime`` by range (values past the map route to the last
  shard, whose cold tier extends the fabric), ``nth_prime`` by walking
  cumulative per-shard totals and forwarding ``k - primes_below`` to the
  owning shard (shard servers anchor at ``range_lo``, so their ``nth``
  is natively "k-th prime >= shard.lo").
* ``pi(x)`` / ``count(lo, hi)`` — scatter-gather as the sum of
  fully-covered shard TOTALS (cached forever: a full-shard prime count
  is an immutable math fact) plus at most two boundary-shard counts.
* ``count(lo, hi, twins|cousins)`` — per-shard pair counts (both
  members inside the shard window) plus an edge SPLICE per interior
  boundary E: primes in [E-gap, E) from the left shard and [E, E+gap)
  from the right are matched to count the pairs that straddle E —
  the same boundary-window trick the mesh merge uses for cross-device
  pairs. ``ShardMap.MIN_SPAN`` guarantees a pair straddles at most one
  edge.
* ``primes(lo, hi)`` — per-shard enumerations concatenated ascending.

Failure semantics compose from the PR 8 client: per-shard failover and
circuit state live in each ReplicaSet (with ``probe_ttl_s`` so shard
selection never adds a probe round-trip on the hot path); a shard whose
replicas are all gone — or held down by the ``svc_shard_down`` chaos
kind — surfaces as a typed ``unavailable`` reply NAMING the shard, and
downstream typed sheds (``overloaded`` with its lane, ``degraded``,
``draining``, ``deadline_exceeded`` with its partial) are relayed with
a ``shard`` field attached. Deadline budgeting forwards the *remaining*
deadline to every downstream call; scatters always run ascending, so a
mid-scatter deadline yields the same contiguous-prefix partial contract
the single server keeps.

Fleet tracing (ISSUE 12): every routed query carries a trace context —
the client's own, or one minted here (``run_id/<seq>.0``) — and each
downstream call forwards a child context (``<ctx>/s<shard>.<call>``,
plus the ReplicaSet's per-attempt suffix), so a shard's ``rpc.query``
spans are prefix-correlated children of this router's ``rpc.route``.
When the router is tracing it also asks shards to piggyback their
bounded span rings on terminal replies; each payload is rebased onto
the router's timeline via per-replica min-RTT clock alignment (every
reply echoes receive/send timestamps — the same NTP-style estimator
the cluster coordinator uses) and ingested under a synthetic per-replica
pid, so one ``--trace`` file carries the router plus a track per shard
replica. A reply that should have carried telemetry but didn't
(``svc_trace_drop`` chaos, or a malformed payload) degrades to
uncorrelated spans: counted in ``telemetry_gaps``, evented as
``router_trace_gap``, never an error.
"""

from __future__ import annotations

import dataclasses
import math
import socket
import threading
import types
import uuid
from typing import Any

from sieve.chaos import (
    ANY_WORKER,
    ChaosSchedule,
    PROFILE_KINDS,
    ROUTER_REQUEST_KINDS,
    parse_chaos,
)
from sieve.enumerate import MAX_HI
from sieve.debug import FlightRecorder
from sieve.profile import StackProfiler
from sieve.metrics import MetricsHistory, MetricsLogger, registry
import numpy as np

from sieve.rpc import (
    SUPPORTED_WIRE,
    WIRE_V1,
    WIRE_V2,
    BatchOutcomes,
    batch_cols_to_items,
    encode_msg,
    encode_msg_v2,
    parse_addr,
    recv_msg,
)
from sieve.service.client import CallTimeout, ReplicaSet, ServiceError
from sieve.service.exemplar import EXEMPLAR_SPAN_RING, ExemplarSampler
from sieve.service.server import BadRequest, DeadlineExceeded, Draining
from sieve.service.shards import ShardMap
from sieve import trace
from sieve.analysis.lockdebug import named_lock

_PAIR_GAP = {"twins": 2, "cousins": 4}

# error kinds a downstream shard can reply with that the router relays
# verbatim (plus a "shard" field); anything else is the router's own
_RELAY_KINDS = frozenset({
    "overloaded", "degraded", "draining", "deadline_exceeded",
    "bad_request", "internal", "timeout",
})

# server wire message types the router deliberately does NOT route
# (tools/check_wire_ops.py audits this list against both dispatchers):
# "telemetry" is a per-replica span-ring flush — the router pulls it
# from each replica itself via ReplicaSet.telemetry_flush, so a client
# sending it to the router gets the standard typed unknown-type reply.
UNROUTED_TYPES = ("telemetry",)


class ShardUnavailable(Exception):
    """A shard's whole replica set is unreachable (or chaos-held down)."""

    def __init__(self, shard: int, lo: int, hi: int, reason: str):
        super().__init__(
            f"shard {shard} [{lo}, {hi}) unavailable: {reason}"
        )
        self.shard = shard
        self.lo = lo
        self.hi = hi
        self.reason = reason


class _Relay(Exception):
    """A downstream typed error to forward as-is, tagged with its shard."""

    def __init__(self, reply: dict, shard: int):
        super().__init__(reply.get("detail", reply.get("error", "")))
        self.reply = reply
        self.shard = shard


@dataclasses.dataclass
class RouterSettings:
    """Router knobs; validated at construction like ServiceSettings."""

    default_deadline_s: float = 30.0
    # downstream ReplicaSet shape
    timeout_s: float = 60.0
    probe_timeout_s: float = 2.0
    # probe freshness window (satellite 2): per-request shard selection
    # must not pay a health round-trip, so probes are cached this long
    probe_ttl_s: float = 2.0
    rounds: int = 2
    drain_s: float = 5.0
    wire_chaos: bool = False
    quiet: bool = False
    # flight recorder (ISSUE 13): same black box as ServiceSettings —
    # shard_down is the router's edge trigger; debug_dir is where
    # bundles freeze (None = inline-only via the ``debug`` wire op)
    recorder: bool = True
    debug_dir: str | None = None
    debug_cooldown_s: float = 30.0
    metrics_sample_s: float = 1.0
    # binary wire v2 (ISSUE 16): False makes this a v1-only router —
    # hello answers ``wire: 1`` upstream AND the downstream shard legs
    # skip negotiation (the mixed-fleet simulation knob)
    wire_v2: bool = True
    # tail-sampled exemplars (ISSUE 19): same sampler as the service,
    # applied at route completion. A kept route also pulls the touched
    # shards' exemplars for its trace context (the ``exemplars`` wire
    # op), so a slow route and its downstream query land in one record.
    # Env spellings are shared with the service (SIEVE_SVC_EXEMPLAR_*).
    exemplars: bool = True
    exemplar_slack: float = 2.0
    exemplar_baseline: int = 100
    exemplar_window: int = 256
    exemplar_warmup: int = 30
    exemplar_ring: int = 256
    exemplar_file_bytes: int = 4 << 20
    # always-on continuous profiler (ISSUE 20): same sampler as the
    # service (shared SIEVE_PROF_* env spellings); prof_hz=0 disables
    prof_hz: float = 19.0
    prof_stacks: int = 512
    prof_idle: bool = False

    def validate(self) -> "RouterSettings":
        for name in ("default_deadline_s", "timeout_s", "probe_timeout_s"):
            v = getattr(self, name)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v <= 0 or not math.isfinite(v):
                raise ValueError(
                    f"router settings: {name}={v!r} must be a positive "
                    "number"
                )
        for name in ("probe_ttl_s", "drain_s", "debug_cooldown_s",
                     "metrics_sample_s"):
            v = getattr(self, name)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v < 0 or not math.isfinite(v):
                raise ValueError(
                    f"router settings: {name}={v!r} must be a non-negative "
                    "number"
                )
        if not isinstance(self.rounds, int) or isinstance(self.rounds, bool) \
                or self.rounds < 1:
            raise ValueError(
                f"router settings: rounds={self.rounds!r} must be a "
                "positive integer"
            )
        for name in ("exemplar_baseline", "exemplar_window",
                     "exemplar_ring", "exemplar_file_bytes",
                     "prof_stacks"):
            v = getattr(self, name)
            if not isinstance(v, int) or isinstance(v, bool) or v <= 0:
                raise ValueError(
                    f"router settings: {name}={v!r} must be a positive "
                    "integer"
                )
        if (not isinstance(self.prof_hz, (int, float))
                or isinstance(self.prof_hz, bool) or self.prof_hz < 0
                or not math.isfinite(self.prof_hz)):
            raise ValueError(
                f"router settings: prof_hz={self.prof_hz!r} must be a "
                "non-negative number"
            )
        if (not isinstance(self.exemplar_warmup, int)
                or isinstance(self.exemplar_warmup, bool)
                or self.exemplar_warmup < 0):
            raise ValueError(
                f"router settings: exemplar_warmup="
                f"{self.exemplar_warmup!r} must be a non-negative integer"
            )
        if (not isinstance(self.exemplar_slack, (int, float))
                or isinstance(self.exemplar_slack, bool)
                or self.exemplar_slack < 1.0
                or not math.isfinite(self.exemplar_slack)):
            raise ValueError(
                f"router settings: exemplar_slack="
                f"{self.exemplar_slack!r} must be a number >= 1"
            )
        return self

    @classmethod
    def from_env(cls, **overrides: Any) -> "RouterSettings":
        """Defaults + the shared SIEVE_SVC_EXEMPLAR_* env spellings
        (the router has far fewer env knobs than the service; explicit
        overrides — the CLI flags — always win)."""
        from sieve import env

        s = cls(
            exemplars=env.env_flag("SIEVE_SVC_EXEMPLARS", True),
            exemplar_slack=env.env_float(
                "SIEVE_SVC_EXEMPLAR_SLACK", cls.exemplar_slack
            ),
            exemplar_baseline=env.env_int(
                "SIEVE_SVC_EXEMPLAR_BASELINE", cls.exemplar_baseline
            ),
            exemplar_window=env.env_int(
                "SIEVE_SVC_EXEMPLAR_WINDOW", cls.exemplar_window
            ),
            exemplar_warmup=env.env_int(
                "SIEVE_SVC_EXEMPLAR_WARMUP", cls.exemplar_warmup
            ),
            exemplar_ring=env.env_int(
                "SIEVE_SVC_EXEMPLAR_RING", cls.exemplar_ring
            ),
            exemplar_file_bytes=env.env_int(
                "SIEVE_SVC_EXEMPLAR_FILE_BYTES", cls.exemplar_file_bytes
            ),
            prof_hz=env.env_float("SIEVE_PROF_HZ", cls.prof_hz),
            prof_stacks=env.env_int("SIEVE_PROF_STACKS", cls.prof_stacks),
            prof_idle=env.env_flag("SIEVE_PROF_IDLE", False),
        )
        return dataclasses.replace(s, **overrides)


class _RouteCtx:
    """Per-request scatter bookkeeping: which shards were touched, the
    contiguous prefix answered so far (for typed partials), splices,
    and the trace context downstream calls derive children from."""

    __slots__ = ("shards", "answered_hi", "count_so_far", "spliced",
                 "ctx", "calls")

    def __init__(self) -> None:
        self.shards: set[int] = set()
        self.answered_hi = 2
        self.count_so_far = 0
        self.spliced = 0
        self.ctx = ""
        self.calls = 0  # downstream calls made — numbers child contexts


_ROUTER_STATS = (
    "requests",
    "routed_point",
    "scattered",
    "spliced",
    "shard_errors",
    "unavailable_replies",
    "shed_relayed",
    "deadline_exceeded",
    "bad_requests",
    "internal_errors",
    "draining_replies",
    "shard_down_windows",
    "telemetry_merged",
    "telemetry_events",
    "telemetry_gaps",
    # batch plane (ISSUE 14): batch_rpcs counts DOWNSTREAM batch RPCs —
    # the ≤1-per-shard-per-client-batch scatter contract is gated on it
    "batch_requests",
    "batch_members",
    "batch_rpcs",
    # tail-sampled exemplars (ISSUE 19)
    "exemplars_seen",
    "exemplars_kept",
    "exemplar_pulls",
    # continuous profiler (ISSUE 20)
    "profile_pulls",
    "profile_gaps",
)

# synthetic pid base for per-shard-replica tracks in the merged trace
# (the cluster merge uses 1_000_000 + worker id; staying clear of it
# lets one report read a trace that carries both planes)
_REPLICA_PID_BASE = 2_000_000


class SieveRouter:
    """The shard-fabric front door. See the module docstring."""

    def __init__(
        self,
        shardmap: ShardMap,
        settings: RouterSettings | None = None,
        addr: str | None = None,
        chaos_spec: str = "",
    ):
        self.map = shardmap
        self.settings = (settings or RouterSettings()).validate()
        self._addr_req = addr or "127.0.0.1:0"
        # MetricsLogger only reads .quiet off its config; the router has
        # no SieveConfig, so a minimal shim stands in
        self.metrics = MetricsLogger(
            types.SimpleNamespace(quiet=self.settings.quiet)
        )
        s = self.settings
        self.sets = [
            ReplicaSet(
                sh.addrs,
                timeout_s=s.timeout_s,
                probe_timeout_s=s.probe_timeout_s,
                rounds=s.rounds,
                probe_ttl_s=s.probe_ttl_s,
                # shard legs go columnar when both ends speak v2; a
                # v1-only router never even offers (ISSUE 16).
                # keep_arrays: decoded primes columns stay int64 arrays
                # through _primes/_count_pairs and re-encode straight
                # into this router's own reply columns — no JSON and no
                # Python-int round trip anywhere on the path
                negotiate=None if s.wire_v2 else False,
                keep_arrays=True,
            )
            for sh in shardmap
        ]
        self.chaos = ChaosSchedule(parse_chaos(chaos_spec))
        # cumulative-totals cache: _totals[i] = primes in shard i's full
        # declared range — an immutable fact, cached forever once known
        self._totals: dict[int, int] = {}  # guard: _totals_lock
        self._totals_lock = named_lock("SieveRouter._totals_lock")
        # svc_shard_down windows: shard index -> monotonic expiry
        self._down_until: dict[int, float] = {}  # guard: _down_lock
        self._down_lock = named_lock("SieveRouter._down_lock")
        # fleet tracing (ISSUE 12): trace-ctx run id for requests that
        # arrive unstamped, per-replica clock aligners keyed by address,
        # and the synthetic pid each replica's merged track renders under
        self._run_id = uuid.uuid4().hex[:8]
        self._tele_lock = named_lock("SieveRouter._tele_lock")
        self._aligns: dict[str, trace.ClockAlign] = {}  # guard: _tele_lock
        self._replica_pids: dict[str, int] = {}  # guard: _tele_lock
        self._replica_shard: dict[str, int] = {}  # guard: _tele_lock
        self._replica_named: set[str] = set()  # guard: _tele_lock
        self._stats = {k: 0 for k in _ROUTER_STATS}  # guard: _stats_lock
        self._stats_lock = named_lock("SieveRouter._stats_lock")
        self._seq = 0  # guard: _seq_lock
        self._seq_lock = named_lock("SieveRouter._seq_lock")
        self._threads: list[threading.Thread] = []
        self._conns: set[socket.socket] = set()  # guard: _conns_lock
        self._conns_lock = named_lock("SieveRouter._conns_lock")
        self._listener: socket.socket | None = None  # guard: none(set
        # once in start() before the accept thread exists)
        self._bound_addr: str | None = None
        self._closing = False  # guard: none(monotonic stop flag;
        # bool reads are GIL-atomic)
        self._draining = False  # guard: none(monotonic drain flag;
        # a racy reader sheds at most one extra request)
        self._inflight_n = 0  # guard: _inflight_lock
        self._inflight_lock = named_lock("SieveRouter._inflight_lock")
        self.drain_event = threading.Event()
        self._drained = threading.Event()
        # flight recorder (ISSUE 13): armed in start(); router_shard_down
        # is the router's edge trigger
        # continuous profiler (ISSUE 20): built before the recorder so
        # bundles embed its snapshot; per-conn dispatch threads draw the
        # svc_prof_gap chaos on a shared pull counter under _stats_lock
        self.profiler: StackProfiler | None = None
        if s.prof_hz > 0:
            self.profiler = StackProfiler(
                "router",
                hz=s.prof_hz,
                max_stacks=s.prof_stacks,
                include_idle=s.prof_idle,
            )
        self._prof_pulls = 0  # guard: _stats_lock
        self.history: MetricsHistory | None = None
        self.recorder: FlightRecorder | None = None
        if s.recorder:
            self.history = MetricsHistory(sample_s=s.metrics_sample_s)
            self.recorder = FlightRecorder(
                "router",
                debug_dir=s.debug_dir,
                history=self.history,
                config=s,
                logger=self.metrics,
                cooldown_s=s.debug_cooldown_s,
                profiler=self.profiler,
            )
        # tail-sampled exemplars (ISSUE 19): route-completion retention;
        # a kept route embeds the touched shards' downstream exemplars
        # for its trace context under "downstream"
        self.exemplar: ExemplarSampler | None = None
        if s.exemplars:
            self.exemplar = ExemplarSampler(
                "router",
                slack=s.exemplar_slack,
                baseline=s.exemplar_baseline,
                window=s.exemplar_window,
                warmup=s.exemplar_warmup,
                ring=s.exemplar_ring,
                file_bytes=s.exemplar_file_bytes,
                debug_dir=s.debug_dir,
                logger=self.metrics,
            )

    # --- lifecycle -------------------------------------------------------

    @property
    def addr(self) -> str:
        if self._bound_addr is None:
            raise RuntimeError("router not started")
        return self._bound_addr

    def start(self) -> "SieveRouter":
        host, port = parse_addr(self._addr_req)
        self._listener = socket.create_server((host, port))
        self._listener.listen(64)
        bhost, bport = self._listener.getsockname()[:2]
        self._bound_addr = f"{bhost}:{bport}"
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="router-accept")
        t.start()
        self._threads.append(t)
        if self.recorder is not None:
            self.history.start()
            self.recorder.install()
        if self.profiler is not None:
            self.profiler.start()
        if self.exemplar is not None:
            # arm the process tracer's exemplar span ring (independent
            # of full event capture — ``trace.enable`` stays off)
            trace.get_tracer().exemplar_enable(EXEMPLAR_SPAN_RING)
        return self

    def drain(self) -> None:
        """Stop accepting, shed new queries as typed ``draining``, let
        in-flight scatters finish. Idempotent; SIGTERM and the wire
        ``shutdown`` message both land here."""
        if self._draining:
            return
        self._draining = True
        if self._listener is not None:
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
        with self._inflight_lock:
            inflight = self._inflight_n
        self.metrics.event("router_drain", inflight=inflight)
        self.drain_event.set()
        self._maybe_drained()

    def wait_drained(self, timeout: float | None = None) -> bool:
        return self._drained.wait(timeout)

    def _maybe_drained(self) -> None:
        with self._inflight_lock:
            done = self._draining and self._inflight_n == 0
        if done:
            self._drained.set()

    def stop(self) -> None:
        self._closing = True
        if self._listener is not None:
            # shutdown() before close(): a plain close does not wake a
            # thread blocked in accept(), which would stall the join below
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5)
        if trace.enabled():
            # pull the residual span ring out of every shard replica
            # before the connections go away — the batched piggyback
            # only ships full batches, so the tail of the trace lives
            # here until this flush merges it
            for i, rs in enumerate(self.sets):
                try:
                    for reply in rs.telemetry_flush():
                        self._absorb_reply(i, reply)
                except Exception:  # noqa: BLE001 — stop() must not raise
                    pass
        for rs in self.sets:
            rs.close()
        if self.exemplar is not None:
            self.exemplar.close()
        if self.profiler is not None:
            self.profiler.stop()
        if self.recorder is not None:
            self.recorder.uninstall()
            self.history.stop()
        self._drained.set()

    def __enter__(self) -> "SieveRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # --- bookkeeping -----------------------------------------------------

    def _bump(self, name: str, n: int = 1) -> None:
        with self._stats_lock:
            self._stats[name] += n
        registry().counter(f"router.{name}").inc(n)

    def _next_seq(self) -> int:
        with self._seq_lock:
            self._seq += 1
            return self._seq

    def inject_chaos(self, spec: str) -> int:
        ds = parse_chaos(spec)
        self.chaos.extend(ds)
        return len(ds)

    # --- chaos & availability --------------------------------------------

    def _draw_chaos(self, seq: int) -> None:
        """Consume svc_shard_down directives for this request sequence.
        The directive's worker field addresses a shard (ANY = every
        shard); windows extend, never shrink."""
        now = trace.now_s()
        for i in range(len(self.map)):
            for d in self.chaos.take_kinds(i, seq, ROUTER_REQUEST_KINDS):
                secs = float(d.get("param") or 0.0)
                targets = (range(len(self.map))
                           if d.get("worker") == ANY_WORKER else (i,))
                for t in targets:
                    with self._down_lock:
                        self._down_until[t] = max(
                            self._down_until.get(t, 0.0), now + secs
                        )
                    self._bump("shard_down_windows")
                    reason = f"chaos svc_shard_down ({secs}s)"
                    self.metrics.event(
                        "router_shard_down", shard=t, reason=reason,
                    )
                    if self.recorder is not None:
                        self.recorder.trigger(
                            "shard_down", shard=t, reason=reason,
                        )

    def _check_shard_up(self, i: int) -> None:
        with self._down_lock:
            until = self._down_until.get(i, 0.0)
        if trace.now_s() < until:
            sh = self.map.shards[i]
            raise ShardUnavailable(
                i, sh.lo, sh.hi,
                "svc_shard_down window live "
                f"({until - trace.now_s():.2f}s remaining)",
            )

    # --- downstream calls ------------------------------------------------

    def _shard_query(self, i: int, op: str, deadline: float,
                     rctx: _RouteCtx, **params: Any):
        """One downstream call with deadline budgeting + typed relay.

        Raises :class:`DeadlineExceeded` when the budget is spent,
        :class:`ShardUnavailable` when the shard cannot answer at all,
        and :class:`_Relay` for downstream typed errors."""
        self._check_shard_up(i)
        remaining = deadline - trace.now_s()
        if remaining <= 0:
            raise DeadlineExceeded(rctx.answered_hi, rctx.count_so_far)
        rctx.shards.add(i)
        rctx.calls += 1
        # child trace ctx: <route ctx>/s<shard>.<call>; the ReplicaSet
        # appends its own .<attempt>, so the shard-side span context is
        # prefix-correlated with this route AND unique per wire attempt
        child_ctx = f"{rctx.ctx}/s{i}.{rctx.calls}"
        sh = self.map.shards[i]
        t0 = trace.now_s()
        outcome = "ok"
        try:
            try:
                reply = self.sets[i].query(op, deadline_s=remaining,
                                           ctx=child_ctx,
                                           telemetry=trace.enabled(),
                                           **params)
            except (ServiceError, CallTimeout) as e:
                # ReplicaSet exhaustion ("unavailable") or a poisoned
                # call: the shard as a whole could not answer
                outcome = "unavailable"
                raise ShardUnavailable(i, sh.lo, sh.hi, str(e)) from None
            self._absorb_reply(i, reply)
            if reply.get("ok"):
                return reply["value"]
            outcome = str(reply.get("error", "internal"))
            raise _Relay(reply, i)
        finally:
            trace.add_span("route.scatter", t0, trace.now_s() - t0,
                           shard=i, op=op, outcome=outcome, ctx=child_ctx)

    def _absorb_reply(self, shard: int, reply: dict) -> None:
        """Fold one downstream reply's trace freight into the router:
        sample the replica's clock aligner from the echoed timestamps,
        then rebase + ingest any piggybacked span ring under the
        replica's synthetic pid. A reply whose telemetry was dropped or
        mangled degrades to a counted ``router_trace_gap`` — correlation
        is lost for those spans, the query result is untouched."""
        probe = reply.get("probe")
        probe = probe if isinstance(probe, dict) else {}
        addr = probe.get("addr")
        align = None
        if isinstance(addr, str) and addr:
            with self._tele_lock:
                align = self._aligns.get(addr)
                if align is None:
                    align = self._aligns[addr] = trace.ClockAlign()
                    self._replica_pids[addr] = (
                        _REPLICA_PID_BASE + len(self._replica_pids)
                    )
                    self._replica_shard[addr] = shard
            stamps = (probe.get("t_send"), reply.get("t_recv"),
                      reply.get("t_sent"), probe.get("t_done"))
            if all(isinstance(v, (int, float)) and not isinstance(v, bool)
                   for v in stamps):
                align.sample(*stamps)
                reg = registry()
                reg.gauge(f"router.replica.{addr}.clock_offset_s").set(
                    round(align.offset_s, 6)
                )
                reg.gauge(f"router.replica.{addr}.clock_err_s").set(
                    round(align.err_s, 6)
                )
        if "telemetry" not in reply:
            return  # replica not shipping (e.g. in-process embed): fine
        tele = reply.pop("telemetry")
        if not isinstance(tele, dict):
            self._bump("telemetry_gaps")
            self.metrics.event(
                "router_trace_gap", quietable=True, shard=shard,
                reason="dropped" if tele is None else "malformed",
                replica=addr or "?",
            )
            return
        events = tele.get("events") or []
        dropped = int(tele.get("dropped") or 0)
        with self._tele_lock:
            key = addr if isinstance(addr, str) and addr else f"shard{shard}"
            pid = self._replica_pids.get(key)
            if pid is None:
                pid = self._replica_pids[key] = (
                    _REPLICA_PID_BASE + len(self._replica_pids)
                )
                self._replica_shard[key] = shard
            first = key not in self._replica_named
            self._replica_named.add(key)
        off_us = (align.offset_s if align is not None and align.samples
                  else 0.0) * 1e6
        merged: list[dict] = []
        if first:
            merged.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": f"shard{shard} {key}"},
            })
        for e in events:
            e = dict(e)
            if "ts" in e:
                e["ts"] = round(e["ts"] - off_us, 3)
            e["pid"] = pid
            merged.append(e)
        info: dict[str, Any] = {"shard": shard, "replica": key,
                                "events": len(events), "dropped": dropped}
        if align is not None and align.samples:
            info.update(
                offset_s=round(align.offset_s, 6),
                rtt_s=round(align.rtt_s, 6),
                err_s=round(align.err_s, 6),
                samples=align.samples,
            )
        merged.append({
            "name": "clock.align", "ph": "i", "s": "p",
            "ts": round(trace.now_s() * 1e6, 3), "pid": pid, "tid": 0,
            "args": info,
        })
        trace.get_tracer().ingest(merged)
        self._bump("telemetry_merged")
        self._bump("telemetry_events", len(events))
        if first:
            # one track-established event per replica, not one per reply
            self.metrics.event("router_telemetry", quietable=True, **info)

    def _shard_total(self, i: int, deadline: float, rctx: _RouteCtx) -> int:
        """Primes in shard i's full declared range, cached forever."""
        with self._totals_lock:
            if i in self._totals:
                registry().counter("router.totals_hit").inc()
                return self._totals[i]
        registry().counter("router.totals_miss").inc()
        sh = self.map.shards[i]
        total = self._shard_query(i, "count", deadline, rctx,
                                  lo=sh.lo, hi=sh.hi)
        with self._totals_lock:
            self._totals[i] = int(total)
        return int(total)

    # --- routed ops ------------------------------------------------------

    def _execute(self, op: str, msg: dict, deadline: float,
                 rctx: _RouteCtx):
        if op == "pi":
            x = _req_int(msg, "x")
            if x < 0 or x + 1 > MAX_HI:
                raise BadRequest(f"pi({x}): x must be in [0, {MAX_HI})")
            self._bump("scattered")
            return self._count_primes(2, x + 1, deadline, rctx)
        if op == "is_prime":
            x = _req_int(msg, "x")
            if x + 1 > MAX_HI:
                raise BadRequest(f"is_prime({x}): x must be < {MAX_HI}")
            if x < 2:
                return False
            self._bump("routed_point")
            i = self.map.shard_for(x)
            rctx.answered_hi = max(rctx.answered_hi, x)
            return bool(self._shard_query(i, "is_prime", deadline, rctx,
                                          x=x))
        if op == "count":
            lo, hi = _req_int(msg, "lo"), _req_int(msg, "hi")
            kind = str(msg.get("kind", "primes"))
            if hi > MAX_HI:
                raise BadRequest(f"count: hi={hi} exceeds {MAX_HI}")
            if hi < lo:
                raise BadRequest(f"count: hi={hi} < lo={lo}")
            self._bump("scattered")
            if kind == "primes":
                return self._count_primes(lo, hi, deadline, rctx)
            if kind in _PAIR_GAP:
                return self._count_pairs(lo, hi, kind, deadline, rctx)
            raise BadRequest(
                f"count: unknown kind {kind!r} (primes, twins, cousins)"
            )
        if op == "nth_prime":
            k = _req_int(msg, "k")
            if k < 1:
                raise BadRequest(f"nth_prime({k}): k must be >= 1")
            self._bump("routed_point")
            return self._nth_prime(k, deadline, rctx)
        if op == "primes":
            lo, hi = _req_int(msg, "lo"), _req_int(msg, "hi")
            if hi > MAX_HI:
                raise BadRequest(f"primes: hi={hi} exceeds {MAX_HI}")
            if hi < lo:
                raise BadRequest(f"primes: hi={hi} < lo={lo}")
            self._bump("scattered")
            return self._primes(lo, hi, deadline, rctx)
        if op == "batch":
            return self._execute_batch(msg, deadline, rctx)
        raise BadRequest(
            f"unknown op {op!r} (one of pi, is_prime, count, nth_prime, "
            "primes, batch)"
        )

    def _shard_batch(self, i: int, items: list[dict], deadline: float,
                     rctx: _RouteCtx) -> list[dict]:
        """ONE downstream ``batch`` RPC to shard i (the scatter
        contract gated by the ``batch_rpcs`` counter). Same budgeting,
        chaos gate, telemetry absorption, and typed relay as
        :meth:`_shard_query`."""
        self._check_shard_up(i)
        remaining = deadline - trace.now_s()
        if remaining <= 0:
            raise DeadlineExceeded(rctx.answered_hi, rctx.count_so_far)
        rctx.shards.add(i)
        rctx.calls += 1
        child_ctx = f"{rctx.ctx}/s{i}.{rctx.calls}"
        sh = self.map.shards[i]
        self._bump("batch_rpcs")
        t0 = trace.now_s()
        outcome = "ok"
        try:
            try:
                reply = self.sets[i].query("batch", deadline_s=remaining,
                                           ctx=child_ctx,
                                           telemetry=trace.enabled(),
                                           items=items)
            except (ServiceError, CallTimeout) as e:
                outcome = "unavailable"
                raise ShardUnavailable(i, sh.lo, sh.hi, str(e)) from None
            self._absorb_reply(i, reply)
            if reply.get("ok"):
                return reply["value"]
            outcome = str(reply.get("error", "internal"))
            raise _Relay(reply, i)
        finally:
            trace.add_span("route.scatter", t0, trace.now_s() - t0,
                           shard=i, op="batch", outcome=outcome,
                           ctx=child_ctx)

    def _execute_batch(self, msg: dict, deadline: float,
                       rctx: _RouteCtx) -> list[dict]:
        """Routed ``batch`` (ISSUE 14): M member queries fan out as at
        most ONE downstream batch RPC per shard.

        Each member decomposes exactly like its scalar op — is_prime
        routes point to its owning shard; pi/count(kind=primes) split
        into per-shard count sub-queries, with fully-covered shards
        served from the immutable totals cache (a miss rides the same
        batch RPC and fills the cache). Sub-queries are deduped per
        shard, so a batch of M members never costs a shard more than
        its distinct sub-query set in one RPC. A shard that fails
        (unavailable / typed relay / spent deadline) fails ONLY the
        members with a term on it — each gets a typed outcome tagged
        with the shard — while members on healthy shards still answer
        exactly."""
        if "b_op" in msg:
            # columnar v2 request (ISSUE 16): rebuild member dicts and
            # run the ordinary planner — the router's work per member
            # is routing, not decoding, so the dict form costs nothing
            # extra here and the per-shard legs re-pack into columns
            # anyway (each ReplicaSet client negotiates its own wire)
            try:
                items = batch_cols_to_items(
                    msg["b_op"], msg["b_a"], msg["b_b"])
            except (KeyError, TypeError, ValueError):
                raise BadRequest(
                    "batch: malformed b_op/b_a/b_b columns") from None
        else:
            items = msg.get("items")
        if not isinstance(items, list) or not items:
            raise BadRequest("batch: items must be a non-empty list")
        self._bump("batch_requests")
        self._bump("batch_members", len(items))
        self._bump("scattered")
        per_shard: dict[int, dict[tuple, dict]] = {}

        def term(i: int, key: tuple, sub: dict) -> tuple[int, tuple]:
            per_shard.setdefault(i, {}).setdefault(key, sub)
            return (i, key)

        # plan each member:
        #   ("err", outcome) | ("const", op, value)
        #   | ("point", op, term) | ("sum", op, const, [terms])
        plans: list[tuple] = []
        for m in items:
            mop = str(m.get("op", "")) if isinstance(m, dict) else ""
            try:
                if not isinstance(m, dict):
                    raise BadRequest("batch member must be an object")
                if mop == "is_prime":
                    x = _req_int(m, "x")
                    if x + 1 > MAX_HI:
                        raise BadRequest(
                            f"is_prime({x}): x must be < {MAX_HI}"
                        )
                    if x < 2:
                        plans.append(("const", mop, False))
                        continue
                    i = self.map.shard_for(x)
                    plans.append(("point", mop,
                                  term(i, ("is_prime", x),
                                       {"op": "is_prime", "x": x})))
                elif mop in ("pi", "count"):
                    if mop == "pi":
                        x = _req_int(m, "x")
                        if x < 0 or x + 1 > MAX_HI:
                            raise BadRequest(
                                f"pi({x}): x must be in [0, {MAX_HI})"
                            )
                        lo, hi = 2, x + 1
                    else:
                        lo, hi = _req_int(m, "lo"), _req_int(m, "hi")
                        if hi > MAX_HI:
                            raise BadRequest(
                                f"count: hi={hi} exceeds {MAX_HI}"
                            )
                        if hi < lo:
                            raise BadRequest(f"count: hi={hi} < lo={lo}")
                        if str(m.get("kind", "primes")) != "primes":
                            raise BadRequest(
                                "batch count members support "
                                "kind=primes only"
                            )
                        lo = max(lo, 2)
                    if hi <= lo:
                        plans.append(("const", mop, 0))
                        continue
                    if lo < self.map.lo:
                        raise BadRequest(
                            f"{mop}: lo={lo} below the fabric range "
                            f"[{self.map.lo}, ...)"
                        )
                    const = 0
                    terms: list[tuple[int, tuple]] = []
                    for i, a, b in self.map.shards_in(lo, hi):
                        sh = self.map.shards[i]
                        if (a, b) == (sh.lo, sh.hi):
                            with self._totals_lock:
                                cached = self._totals.get(i)
                            if cached is not None:
                                registry().counter("router.totals_hit").inc()
                                const += cached
                                continue
                            registry().counter("router.totals_miss").inc()
                        terms.append(term(i, ("count", a, b),
                                          {"op": "count", "lo": a, "hi": b}))
                    plans.append(("sum", mop, const, terms))
                else:
                    raise BadRequest(
                        f"unknown batch member op {mop!r} "
                        "(one of pi, is_prime, count)"
                    )
            except BadRequest as e:
                plans.append(("err", {
                    "ok": False, "op": mop, "error": "bad_request",
                    "detail": str(e), "partial": None,
                }))
        # scatter: ONE deduped batch RPC per touched shard, ascending
        resolved: dict[tuple[int, tuple], dict] = {}
        for i in sorted(per_shard):
            keys = sorted(per_shard[i])
            subs = [per_shard[i][k] for k in keys]
            fault: dict | None = None
            try:
                outs = self._shard_batch(i, subs, deadline, rctx)
                if not isinstance(outs, list) or len(outs) != len(subs):
                    got = (len(outs) if isinstance(outs, list)
                           else type(outs).__name__)
                    sh = self.map.shards[i]
                    raise ShardUnavailable(
                        i, sh.lo, sh.hi,
                        f"batch reply shape: {got} outcomes for "
                        f"{len(subs)} members",
                    )
            except ShardUnavailable as e:
                self._bump("shard_errors")
                fault = {"error": "unavailable", "detail": str(e),
                         "partial": None}
            except DeadlineExceeded as e:
                fault = {"error": "deadline_exceeded", "detail": str(e),
                         "partial": {"answered_hi": e.answered_hi,
                                     "count_so_far": e.count_so_far}}
            except _Relay as e:
                self._bump("shard_errors")
                fault = {"error": str(e.reply.get("error", "internal")),
                         "detail": e.reply.get("detail", ""),
                         "partial": e.reply.get("partial")}
            if fault is not None:
                for k in keys:
                    resolved[(i, k)] = {"ok": False, "shard": i, **fault}
                continue
            for k, o in zip(keys, outs):
                if not isinstance(o, dict):
                    o = {"ok": False, "error": "internal",
                         "detail": "malformed batch member outcome"}
                if not o.get("ok"):
                    o.setdefault("shard", i)
                elif k[0] == "count":
                    # a full-shard count rode along: fill the totals
                    # cache (immutable math fact, cached forever)
                    sh = self.map.shards[i]
                    if (k[1], k[2]) == (sh.lo, sh.hi):
                        with self._totals_lock:
                            self._totals.setdefault(i, int(o["value"]))
                resolved[(i, k)] = o
        # assemble per-member outcomes, in member order
        out: list[dict] = []
        for plan in plans:
            kind = plan[0]
            if kind == "err":
                out.append(plan[1])
            elif kind == "const":
                out.append({"ok": True, "op": plan[1], "value": plan[2]})
            elif kind == "point":
                o = dict(resolved[plan[2]])
                o["op"] = plan[1]
                if o.get("ok"):
                    o["value"] = bool(o["value"])
                out.append(o)
            else:  # sum
                _, mop, const, terms = plan
                bad = next((resolved[t] for t in terms
                            if not resolved[t].get("ok")), None)
                if bad is not None:
                    o = dict(bad)
                    o["op"] = mop
                    out.append(o)
                else:
                    out.append({"ok": True, "op": mop,
                                "value": const + sum(
                                    int(resolved[t]["value"])
                                    for t in terms)})
        return out

    @staticmethod
    def _partial(op: str, rctx: _RouteCtx) -> dict:
        """Typed partial in the single server's key schema: the fabric
        prefix [map.lo, answered_hi) is fully answered."""
        if op == "pi":
            return {"answered_hi": rctx.answered_hi,
                    "pi_so_far": rctx.count_so_far}
        if op == "nth_prime":
            return {"searched_hi": rctx.answered_hi,
                    "count_so_far": rctx.count_so_far}
        return {"answered_hi": rctx.answered_hi,
                "count_so_far": rctx.count_so_far}

    def _fold_partial(self, e: _Relay, rctx: _RouteCtx) -> None:
        """A downstream deadline partial is a contiguous prefix of ITS
        shard window; since scatters run ascending, folding it into the
        route context keeps the fabric-level prefix contiguous too."""
        p = e.reply.get("partial") or {}
        hi = p.get("answered_hi", p.get("searched_hi"))
        if isinstance(hi, int):
            rctx.answered_hi = max(rctx.answered_hi, hi)
        c = p.get("count_so_far", p.get("pi_so_far"))
        if isinstance(c, int):
            rctx.count_so_far += c

    def _count_primes(self, lo: int, hi: int, deadline: float,
                      rctx: _RouteCtx) -> int:
        lo = max(lo, 2)
        if hi <= lo:
            return 0
        if lo < self.map.lo:
            raise BadRequest(
                f"count: lo={lo} below the fabric range "
                f"[{self.map.lo}, ...)"
            )
        total = 0
        for i, a, b in self.map.shards_in(lo, hi):
            sh = self.map.shards[i]
            if (a, b) == (sh.lo, sh.hi):
                v = self._shard_total(i, deadline, rctx)
            else:
                v = self._shard_query(i, "count", deadline, rctx,
                                      lo=a, hi=b)
            total += int(v)
            rctx.answered_hi = max(rctx.answered_hi, b)
            rctx.count_so_far = total
        return total

    def _count_pairs(self, lo: int, hi: int, kind: str, deadline: float,
                     rctx: _RouteCtx) -> int:
        gap = _PAIR_GAP[kind]
        lo = max(lo, 2)
        if hi <= lo:
            return 0
        if lo < self.map.lo:
            raise BadRequest(
                f"count: lo={lo} below the fabric range "
                f"[{self.map.lo}, ...)"
            )
        parts = self.map.shards_in(lo, hi)
        total = 0
        # pairs fully inside one shard window
        for i, a, b in parts:
            total += int(self._shard_query(i, "count", deadline, rctx,
                                           lo=a, hi=b, kind=kind))
        # splice each interior edge E: a straddling pair (p, p+gap) has
        # p in [E-gap, E) on the left shard and p+gap in [E, E+gap) on
        # the right — MIN_SPAN guarantees both windows stay inside their
        # shard, so each downstream ask is range-legal
        for (il, _al, bl), (ir, ar, _br) in zip(parts, parts[1:]):
            edge = bl
            assert edge == ar, "shards_in returned non-adjacent parts"
            left_lo = max(lo, edge - gap)
            right_hi = min(hi, edge + gap)
            if left_lo >= edge or right_hi <= edge:
                continue
            left = self._shard_query(il, "primes", deadline, rctx,
                                     lo=left_lo, hi=edge)
            right = set(self._shard_query(ir, "primes", deadline, rctx,
                                          lo=edge, hi=right_hi))
            crossing = sum(1 for p in left if p + gap in right)
            total += crossing
            rctx.spliced += 1
            self._bump("spliced")
            self.metrics.event("router_spliced", quietable=True,
                               edge=edge, pair_kind=kind, pairs=crossing)
        return total

    def _nth_prime(self, k: int, deadline: float, rctx: _RouteCtx) -> int:
        cum = 0
        last = len(self.map) - 1
        for i in range(len(self.map)):
            if i == last:
                # the last shard extends past the map via its cold tier;
                # whatever k remains, it owns the answer
                return int(self._shard_query(i, "nth_prime", deadline,
                                             rctx, k=k - cum))
            total = self._shard_total(i, deadline, rctx)
            if cum + total >= k:
                return int(self._shard_query(i, "nth_prime", deadline,
                                             rctx, k=k - cum))
            cum += total
            rctx.answered_hi = max(rctx.answered_hi,
                                   self.map.shards[i].hi)
            rctx.count_so_far = cum
        raise AssertionError("unreachable: last shard handles any k")

    def _primes(self, lo: int, hi: int, deadline: float,
                rctx: _RouteCtx) -> np.ndarray:
        lo = max(lo, 2)
        if hi <= lo:
            return np.zeros(0, dtype=np.int64)
        if lo < self.map.lo:
            raise BadRequest(
                f"primes: lo={lo} below the fabric range "
                f"[{self.map.lo}, ...)"
            )
        # shard legs deliver int64 arrays (keep_arrays clients decode
        # the binary columns straight into them); v1 shards hand lists,
        # normalized here once — member order is ascending by shard
        parts: list[np.ndarray] = []
        count = 0
        for i, a, b in self.map.shards_in(lo, hi):
            vals = np.asarray(
                self._shard_query(i, "primes", deadline, rctx,
                                  lo=a, hi=b),
                dtype=np.int64,
            )
            parts.append(vals)
            count += int(vals.size)
            rctx.answered_hi = max(rctx.answered_hi, b)
            rctx.count_so_far = count
        return (np.concatenate(parts) if parts
                else np.zeros(0, dtype=np.int64))

    # --- control plane ---------------------------------------------------

    def health(self) -> dict:
        """Aggregate health: per-shard depth/brownout/covered_hi plus the
        fabric's contiguous covered range (covered_hi stops at the first
        shard that is unreachable or still behind its declared range)."""
        shards_out = []
        covered_hi = self.map.lo
        contiguous = True
        degraded = False
        now = trace.now_s()
        for i, sh in enumerate(self.map.shards):
            with self._down_lock:
                held_down = now < self._down_until.get(i, 0.0)
            ent: dict[str, Any] = {"shard": i, "lo": sh.lo, "hi": sh.hi,
                                   "addrs": list(sh.addrs)}
            if held_down:
                ent["status"] = "unavailable"
                ent["detail"] = "svc_shard_down window live"
            else:
                try:
                    h = self.sets[i].health()
                    ent["status"] = h.get("status", "ok")
                    ent["covered_hi"] = h.get("covered_hi")
                    ent["queue_depth"] = h.get("queue_depth")
                    ent["brownout"] = h.get("brownout")
                    ent["draining"] = h.get("draining")
                    gauges = registry()
                    gauges.gauge(f"router.shard{i}.queue_depth").set(
                        float(h.get("queue_depth") or 0)
                    )
                    gauges.gauge(f"router.shard{i}.covered_hi").set(
                        float(h.get("covered_hi") or 0)
                    )
                except ServiceError as e:
                    ent["status"] = "unavailable"
                    ent["detail"] = e.detail
            if ent["status"] == "unavailable":
                degraded = True
                contiguous = False
            elif contiguous:
                # the fabric's contiguous covered range stops at the
                # first shard whose index falls short of its slice; the
                # last shard's cold-grown coverage extends past the map
                sh_cov = int(ent.get("covered_hi") or sh.lo)
                is_last = i == len(self.map) - 1
                covered_hi = max(
                    covered_hi, sh_cov if is_last else min(sh_cov, sh.hi)
                )
                if sh_cov < sh.hi:
                    contiguous = False
            if ent.get("status") == "degraded":
                degraded = True
            shards_out.append(ent)
        return {
            "type": "health", "ok": True,
            "status": "degraded" if degraded else "ok",
            "role": "router",
            "shard_count": len(self.map),
            "range_lo": self.map.lo,
            "range_hi": self.map.hi,
            "covered_hi": covered_hi,
            "draining": self._draining,
            "shards": shards_out,
        }

    def stats(self) -> dict:
        with self._stats_lock:
            out = dict(self._stats)
        out["shard_count"] = len(self.map)
        out["range_lo"] = self.map.lo
        out["range_hi"] = self.map.hi
        with self._totals_lock:
            out["totals_cached"] = len(self._totals)
        out["draining"] = self._draining
        out["probes"] = sum(rs.probes for rs in self.sets)
        out["failovers"] = sum(rs.failovers for rs in self.sets)
        # ISSUE 16: shard connections that came up v1-only — a nonzero
        # value on a supposedly all-v2 fleet is the downgrade signal
        out["wire_downgrades"] = sum(rs.downgrades for rs in self.sets)
        return out

    # --- network plumbing ------------------------------------------------

    def _accept_loop(self) -> None:
        assert self._listener is not None
        while not self._closing:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            try:
                # hot RPC path: replies leave on send, not on the
                # peer's delayed ACK (same knob as the shard server)
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            with self._conns_lock:
                self._conns.add(conn)
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
                name="router-conn",
            )
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        send_lock = threading.Lock()
        # per-connection negotiated send version (ISSUE 16): a mutable
        # cell rather than a conn attribute — this thread owns the conn,
        # only the hello branch writes it
        state = {"wire_v": WIRE_V1}  # guard: none(owned by this
        # conn's serve thread; the hello branch is the only writer and
        # runs on the same thread as every reader)
        try:
            while not self._closing:
                try:
                    msg = recv_msg(conn)
                except (OSError, ValueError):
                    return
                if msg is None:
                    return
                self._dispatch(conn, send_lock, msg, state)
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _reply(self, conn: socket.socket, send_lock: threading.Lock,
               payload: dict, cols: dict | None = None) -> None:
        frame = (encode_msg_v2(payload, cols) if cols
                 else encode_msg(payload))
        try:
            with send_lock:
                conn.sendall(frame)
        except OSError:
            pass

    def _dispatch(self, conn, send_lock, msg: dict, state: dict) -> None:
        mtype = msg.get("type")
        rid = msg.get("id")
        if mtype == "hello":
            # wire-version negotiation (ISSUE 16): same contract as the
            # shard server — highest mutual version, v1 JSON floor. A
            # wire_v2=False router answers 1, and its v2-capable caller
            # logs the wire_downgrade.
            try:
                peer = {int(v) for v in (msg.get("wire") or ())
                        if not isinstance(v, bool)}
            except (TypeError, ValueError):
                peer = set()
            mine = set(SUPPORTED_WIRE) if self.settings.wire_v2 \
                else {WIRE_V1}
            mutual = peer & mine
            state["wire_v"] = max(mutual) if mutual else WIRE_V1
            self._reply(conn, send_lock,
                        {"type": "hello", "id": rid, "ok": True,
                         "wire": state["wire_v"],
                         "versions": sorted(mine)})
            return
        if mtype == "health":
            h = self.health()
            h["id"] = rid
            self._reply(conn, send_lock, h)
            return
        if mtype == "stats":
            self._reply(conn, send_lock,
                        {"type": "stats", "id": rid, "ok": True,
                         "stats": self.stats()})
            return
        if mtype == "metrics":
            # live telemetry plane (ISSUE 12): the full registry
            # snapshot, answered inline so fleet_top keeps seeing it
            # even while the query plane is under pressure
            self._reply(conn, send_lock,
                        {"type": "metrics", "id": rid, "ok": True,
                         "role": "router",
                         "metrics": registry().snapshot()})
            return
        if mtype == "debug":
            # flight-recorder freeze (ISSUE 13): inline like metrics
            self._reply(conn, send_lock, {
                "type": "debug", "id": rid, "ok": True, "role": "router",
                "bundle": (self.recorder.snapshot("manual")
                           if self.recorder is not None else None),
            })
            return
        if mtype == "profile":
            # continuous-profiler pull (ISSUE 20): inline like debug.
            # svc_prof_gap chaos drops the K-th reply (puller times
            # out) and pauses the sampler one beat; the shared pull
            # counter lives under _stats_lock (per-conn threads).
            with self._stats_lock:
                self._prof_pulls += 1
                pulls = self._prof_pulls
            gap = bool(self.chaos.take_kinds(0, pulls, PROFILE_KINDS))
            snap = (self.profiler.snapshot()
                    if self.profiler is not None else None)
            self.metrics.event(
                "profile_pulled", quietable=True, role="router",
                samples=(snap or {}).get("samples"),
                stacks=len((snap or {}).get("stacks") or ()), gap=gap,
            )
            if gap:
                self._bump("profile_gaps")
                if self.profiler is not None:
                    self.profiler.pause(1)
                return
            self._bump("profile_pulls")
            self._reply(conn, send_lock, {
                "type": "profile", "id": rid, "ok": True,
                "role": "router", "profile": snap,
            })
            return
        if mtype == "exemplars":
            # kept-exemplar pull (ISSUE 19): the router's own ring —
            # each record already embeds its downstream shard exemplars
            ctx_f = msg.get("ctx")
            n_f = msg.get("n")
            self._reply(conn, send_lock, {
                "type": "exemplars", "id": rid, "ok": True,
                "role": "router",
                "exemplars": (self.exemplar.tail(
                    n=n_f if isinstance(n_f, int) else None,
                    ctx_prefix=ctx_f if isinstance(ctx_f, str) else None,
                ) if self.exemplar is not None else []),
            })
            return
        if mtype == "shutdown":
            self._reply(conn, send_lock,
                        {"type": "reply", "id": rid, "ok": True,
                         "draining": True})
            self.drain()
            return
        if mtype == "chaos":
            if not self.settings.wire_chaos:
                self.metrics.event("router_chaos_refused",
                                   spec=str(msg.get("spec", "")))
                self._reply(conn, send_lock, {
                    "type": "reply", "id": rid, "ok": False,
                    "error": "bad_request",
                    "detail": "wire chaos injection is disabled on this "
                              "router (start it with --allow-chaos)",
                })
                return
            try:
                n = self.inject_chaos(str(msg.get("spec", "")))
            except ValueError as e:
                self._reply(conn, send_lock,
                            {"type": "reply", "id": rid, "ok": False,
                             "error": "bad_request", "detail": str(e)})
                return
            self._reply(conn, send_lock,
                        {"type": "reply", "id": rid, "ok": True,
                         "injected": n})
            return
        if mtype != "query":
            self._reply(conn, send_lock, {
                "type": "reply", "id": rid, "ok": False,
                "error": "bad_request",
                "detail": f"unknown message type {mtype!r}",
            })
            return
        self._handle_query(conn, send_lock, msg, rid, state)

    def _handle_query(self, conn, send_lock, msg: dict, rid,
                      state: dict) -> None:
        with self._inflight_lock:
            self._inflight_n += 1
        try:
            self._handle_query_inner(conn, send_lock, msg, rid, state)
        finally:
            with self._inflight_lock:
                self._inflight_n -= 1
            self._maybe_drained()

    def _handle_query_inner(self, conn, send_lock, msg: dict, rid,
                            state: dict) -> None:
        op = str(msg.get("op", ""))
        t0 = trace.now_s()
        seq = self._next_seq()
        self._bump("requests")
        self._draw_chaos(seq)
        rctx = _RouteCtx()
        # adopt the client's trace ctx, or mint one so downstream child
        # contexts are well-formed even for unstamped (old) clients
        mctx = msg.get("ctx")
        rctx.ctx = (mctx if isinstance(mctx, str) and mctx
                    else f"{self._run_id}/{seq}.0")
        outcome = "ok"
        reply: dict = {"type": "reply", "id": rid, "ok": True, "op": op}
        try:
            if self._draining:
                raise Draining("router is draining; new queries are shed")
            raw = msg.get("deadline_s")
            if raw is not None and (
                not isinstance(raw, (int, float)) or isinstance(raw, bool)
                or raw <= 0 or not math.isfinite(raw)
            ):
                raise BadRequest(
                    f"deadline_s must be a positive number, got {raw!r}"
                )
            deadline = t0 + float(raw or self.settings.default_deadline_s)
            reply["value"] = self._execute(op, msg, deadline, rctx)
        except _Relay as e:
            down = e.reply
            outcome = str(down.get("error", "internal"))
            if outcome not in _RELAY_KINDS:
                outcome = "internal"
            self._bump("shard_errors")
            if outcome == "deadline_exceeded":
                # fold the shard's contiguous partial into the route's:
                # scatters run ascending, so the fabric-level prefix
                # [2, answered_hi) stays contiguous
                self._fold_partial(e, rctx)
                self._bump("deadline_exceeded")
                reply = {
                    "type": "reply", "id": rid, "ok": False, "op": op,
                    "error": "deadline_exceeded",
                    "detail": down.get("detail", ""),
                    "partial": self._partial(op, rctx),
                    "shard": e.shard,
                }
            else:
                # forwarded verbatim + shard tag (lane rides along on
                # an overloaded shed — lane-aware propagation)
                reply = {
                    "type": "reply", "id": rid, "ok": False, "op": op,
                    "error": outcome,
                    "detail": down.get("detail", ""),
                    "partial": down.get("partial"),
                    "shard": e.shard,
                }
                if "lane" in down:
                    reply["lane"] = down["lane"]
                if outcome in ("overloaded", "degraded", "draining"):
                    self._bump("shed_relayed")
        except ShardUnavailable as e:
            outcome = "unavailable"
            reply = {
                "type": "reply", "id": rid, "ok": False, "op": op,
                "error": "unavailable", "detail": str(e),
                "partial": None, "shard": e.shard,
                "shard_range": [e.lo, e.hi],
            }
            self._bump("shard_errors")
            self._bump("unavailable_replies")
            self.metrics.event("router_shard_down", shard=e.shard,
                               reason=e.reason)
            if self.recorder is not None:
                self.recorder.trigger("shard_down", shard=e.shard,
                                      reason=e.reason)
        except DeadlineExceeded as e:
            outcome = "deadline_exceeded"
            rctx.answered_hi = max(rctx.answered_hi, e.answered_hi)
            rctx.count_so_far = max(rctx.count_so_far, e.count_so_far)
            reply = {
                "type": "reply", "id": rid, "ok": False, "op": op,
                "error": "deadline_exceeded", "detail": str(e),
                "partial": self._partial(op, rctx),
            }
            self._bump("deadline_exceeded")
        except BadRequest as e:
            outcome = "bad_request"
            reply = {
                "type": "reply", "id": rid, "ok": False, "op": op,
                "error": "bad_request", "detail": str(e), "partial": None,
            }
            self._bump("bad_requests")
        except Draining as e:
            outcome = "draining"
            reply = {
                "type": "reply", "id": rid, "ok": False, "op": op,
                "error": "draining", "detail": str(e), "partial": None,
            }
            self._bump("draining_replies")
        except Exception as e:  # noqa: BLE001 — router must not die
            outcome = "internal"
            reply = {
                "type": "reply", "id": rid, "ok": False, "op": op,
                "error": "internal",
                "detail": f"{type(e).__name__}: {e}", "partial": None,
            }
            self._bump("internal_errors")
        t_end = trace.now_s()
        reply.setdefault("source", "router")
        reply["elapsed_ms"] = round((t_end - t0) * 1000, 3)
        if isinstance(msg.get("t_send"), (int, float)) \
                and not isinstance(msg.get("t_send"), bool):
            # echo receive/send stamps so a tracing CALLER (a client, or
            # a router-of-routers) can clock-align against this process
            reply["t_recv"] = round(t0, 6)
            reply["t_sent"] = round(t_end, 6)
        trace.add_span("rpc.route", t0, t_end - t0, op=op, outcome=outcome,
                       shards=len(rctx.shards), ctx=rctx.ctx)
        self.metrics.event(
            "router_request", quietable=True, op=op, outcome=outcome,
            shards=len(rctx.shards), ms=reply["elapsed_ms"],
        )
        # reply finalization (ISSUE 16): array/batch values go out as v2
        # columns on a negotiated connection — the shard legs already
        # delivered them as arrays (keep_arrays), so a routed primes
        # window is never JSON-encoded per element anywhere on its path
        cols = None
        val = reply.get("value")
        if isinstance(val, np.ndarray):
            if state["wire_v"] >= WIRE_V2:
                del reply["value"]
                # values column, not bitset words: the window spans
                # shards whose packings may differ, and the router has
                # no layout of its own to re-pack against
                reply.update({"vkind": "primes", "prepr": "values"})
                cols = {"p_vals": val.astype("<i8", copy=False)}
            else:
                reply["value"] = val.tolist()
        elif (op == "batch" and isinstance(val, list)
                and state["wire_v"] >= WIRE_V2):
            bo = BatchOutcomes.from_items(val)
            del reply["value"]
            extra, cols = bo.wire()
            reply.update(extra)
        self._reply(conn, send_lock, reply, cols=cols)
        # tail-sampled exemplar (ISSUE 19), AFTER the reply: a kept
        # route pulls the touched shards' exemplars for this trace
        # context (the ``exemplars`` wire op), so the downstream pull's
        # RPC cost never rides on the client's latency
        if self.exemplar is not None:
            self._bump("exemplars_seen")
            reason = self.exemplar.decide(outcome, reply["elapsed_ms"])
            if reason is not None:
                self._bump("exemplars_kept")
                downstream: list[dict] = []
                for si in sorted(rctx.shards):
                    if 0 <= si < len(self.sets):
                        self._bump("exemplar_pulls")
                        for rec in self.sets[si].exemplars(ctx=rctx.ctx):
                            rec["shard"] = si
                            downstream.append(rec)
                self.exemplar.keep({
                    "ctx": rctx.ctx,
                    "op": op,
                    "outcome": outcome,
                    "ms": reply["elapsed_ms"],
                    "shards": sorted(rctx.shards),
                    "reason": reason,
                    "spans": trace.exemplar_collect(rctx.ctx),
                    "downstream": downstream,
                })


def _req_int(msg: dict, field: str) -> int:
    v = msg.get(field)
    if not isinstance(v, int) or isinstance(v, bool):
        raise BadRequest(f"field {field!r} must be an integer, got {v!r}")
    return v
