"""Blocking client for the query service (one socket, one request at a
time). Concurrency = one client per thread; the framing and the server's
per-connection send lock keep each connection's request/reply stream
ordered, so a synchronous client never sees an interleaved reply.

Typed errors surface as :class:`ServiceError` with the server's error
kind (``overloaded`` / ``deadline_exceeded`` / ``degraded`` /
``bad_request`` / ``internal``) and any partial answer; callers that
want the raw reply dict (tools/service_smoke.py inspects typed outcomes)
use :meth:`ServiceClient.query`.
"""

from __future__ import annotations

import itertools
import socket
from typing import Any

from sieve.rpc import parse_addr, recv_msg, send_msg


class ServiceError(RuntimeError):
    def __init__(self, kind: str, detail: str, partial: dict | None = None):
        super().__init__(f"{kind}: {detail}")
        self.kind = kind
        self.detail = detail
        self.partial = partial


class ServiceClient:
    def __init__(self, addr: str, timeout_s: float = 60.0):
        host, port = parse_addr(addr)
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._ids = itertools.count(1)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ServiceClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --- raw -------------------------------------------------------------

    def _call(self, msg: dict) -> dict:
        msg.setdefault("id", next(self._ids))
        send_msg(self._sock, msg)
        reply = recv_msg(self._sock)
        if reply is None:
            raise ConnectionError("service closed the connection")
        return reply

    def query(self, op: str, deadline_s: float | None = None,
              **params: Any) -> dict:
        """One query; returns the raw reply dict (ok or typed error)."""
        msg: dict[str, Any] = {"type": "query", "op": op, **params}
        if deadline_s is not None:
            msg["deadline_s"] = deadline_s
        return self._call(msg)

    def _value(self, reply: dict):
        if reply.get("ok"):
            return reply["value"]
        raise ServiceError(
            reply.get("error", "internal"),
            reply.get("detail", ""),
            reply.get("partial"),
        )

    # --- ops -------------------------------------------------------------

    def pi(self, x: int, deadline_s: float | None = None) -> int:
        return self._value(self.query("pi", deadline_s, x=x))

    def count(self, lo: int, hi: int, kind: str = "primes",
              deadline_s: float | None = None) -> int:
        return self._value(
            self.query("count", deadline_s, lo=lo, hi=hi, kind=kind)
        )

    def nth_prime(self, k: int, deadline_s: float | None = None) -> int:
        return self._value(self.query("nth_prime", deadline_s, k=k))

    def primes(self, lo: int, hi: int,
               deadline_s: float | None = None) -> list[int]:
        return self._value(self.query("primes", deadline_s, lo=lo, hi=hi))

    # --- control plane ---------------------------------------------------

    def health(self) -> dict:
        return self._call({"type": "health"})

    def stats(self) -> dict:
        return self._call({"type": "stats"})["stats"]

    def inject_chaos(self, spec: str) -> dict:
        return self._call({"type": "chaos", "spec": spec})
