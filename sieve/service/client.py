"""Blocking client for the query service, with optional pipelining.

``pi``/``count``/... are one-request-at-a-time and unchanged. Since the
wire plane went event-loop (ISSUE 14) the server answers replies in
COMPLETION order, not send order — a hot query pipelined behind a cold
one comes back first — so the client correlates replies to requests by
the ``id`` each reply echoes, stashing out-of-order arrivals until
their turn. :meth:`ServiceClient.submit` sends without waiting and
returns the wire id; :meth:`ServiceClient.drain` collects any set of
outstanding replies; :meth:`ServiceClient.query_batch` ships M member
queries in ONE ``batch`` RPC and returns M typed per-member outcomes.
Concurrency is still one client per thread — pipelining happens within
a thread, not across threads.

Typed errors surface as :class:`ServiceError` with the server's error
kind (``overloaded`` / ``deadline_exceeded`` / ``degraded`` /
``draining`` / ``bad_request`` / ``internal``) and any partial answer;
callers that want the raw reply dict (tools/service_smoke.py inspects
typed outcomes) use :meth:`ServiceClient.query`.

A ``socket.timeout`` mid-call poisons the connection: the request is
still in flight server-side, so the *next* recv on that socket would
read this call's reply as its own — silent desync, wrong numbers. The
client closes the socket and raises :class:`CallTimeout` instead; every
later call on the same client fails fast with :class:`ConnectionError`.

:class:`ReplicaSet` (ISSUE 8) wraps N replica addresses behind the same
ops surface with failover: health-probe-based selection, a per-replica
circuit (consecutive connection failures open it for a capped-
exponential cooldown; reuse of a half-open replica re-probes first), and
a retry policy typed per error kind — connection drops / timeouts /
``overloaded`` / ``degraded`` / ``draining`` fail over to the next
replica, while ``bad_request`` and ``deadline_exceeded`` never retry
(the answer would be the same, and a deadline'd retry doubles the spend
the caller bounded). Exhausting every replica across all rounds raises
the last typed error seen, else ``ServiceError("unavailable")`` — the
set never invents an answer.

Every query is stamped with a trace context (ISSUE 12):
``ctx = "<run_id>/<seq>.<attempt>"`` plus a ``t_send`` timestamp on the
sender's trace epoch. The server echoes the ctx into its spans (so a
routed query's shard-side ``rpc.query`` correlates with the router's
``rpc.route``) and echoes receive/send timestamps for NTP-style clock
alignment. A :class:`ReplicaSet` mints a FRESH attempt suffix per try —
two attempts of one logical query are two distinct contexts, so a
retried request never aliases spans from the attempt that failed. With
``telemetry=True`` the reply may piggyback the replica's bounded span
ring (the router asks for this when tracing); the set annotates each
returned reply with a ``probe`` record (addr + its own send/done
timestamps) so the caller can feed the clock aligner.
"""

from __future__ import annotations

import itertools
import random
import socket
import struct
import threading
import time
import uuid
from typing import Any, Sequence

import numpy as np

from sieve import env, trace
from sieve.analysis.lockdebug import named_lock
from sieve.metrics import registry
from sieve.rpc import (
    SUPPORTED_WIRE,
    WIRE_V1,
    WIRE_V2,
    _recv_exact,
    batch_items_to_cols,
    batch_reply_value,
    decode_body,
    encode_msg,
    encode_msg_v2,
    parse_addr,
    primes_reply_value,
)


class ServiceError(RuntimeError):
    def __init__(self, kind: str, detail: str, partial: dict | None = None,
                 shard: int | None = None):
        super().__init__(f"{kind}: {detail}")
        self.kind = kind
        self.detail = detail
        self.partial = partial
        # multi-hop provenance (ISSUE 11): when the reply crossed the
        # router tier, which shard the error originated on (None for a
        # direct single-server reply or a router-level error)
        self.shard = shard


class CallTimeout(ServiceError):
    """The reply didn't arrive within the socket timeout. The connection
    is closed (reply stream desynced) — the request may still complete
    server-side, so the outcome is *unknown*, never assumed failed."""

    def __init__(self, detail: str):
        super().__init__("timeout", detail)


#: lazily-built logger for client-side wire events (the client has no
#: config of its own — same quiet-shim trick the router uses)
_wire_logger = None
_wire_logger_lock = named_lock("client._wire_logger_lock")


def _emit_wire_downgrade(addr: str, negotiated: int) -> None:
    global _wire_logger
    import types as _types

    from sieve.metrics import MetricsLogger

    with _wire_logger_lock:
        if _wire_logger is None:
            _wire_logger = MetricsLogger(_types.SimpleNamespace(quiet=True))
        logger = _wire_logger
    registry().counter("wire.downgrade").inc()
    logger.event("wire_downgrade", quietable=True, addr=addr,
                 negotiated=negotiated)


class ServiceClient:
    def __init__(self, addr: str, timeout_s: float = 60.0,
                 negotiate: bool | None = None, keep_arrays: bool = False):
        host, port = parse_addr(addr)
        self._addr = addr
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        try:
            # request frames are one sendall each; never Nagle-hold the
            # tail segment of a multi-segment binary batch (ISSUE 16)
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._ids = itertools.count(1)
        self._run_id = uuid.uuid4().hex[:8]
        self._ctx_seq = itertools.count(1)
        self._dead = False
        # pipelining (ISSUE 14): ids awaiting a reply (→ send timestamp)
        # and replies that arrived before their turn (id → reply)
        self._pending: dict[Any, float] = {}
        self._replies: dict[Any, dict] = {}
        # binary wire v2 (ISSUE 16): negotiated send version, member-op
        # memory for columnar batches in flight (id → op names), raw
        # wire byte counters (the bytes-per-member bench reads them),
        # and keep_arrays=True hands decoded ``primes`` values out as
        # int64 arrays instead of lists (the router's shard legs — no
        # round trip through Python ints on a pass-through)
        self.wire_v = WIRE_V1  # guard: none(written only during
        # __init__'s hello, before the client is shared; readers after
        # that see a frozen value)
        self.downgraded = False  # guard: none(same write-once-in-init
        # discipline as wire_v)
        self.keep_arrays = keep_arrays
        self.bytes_sent = 0  # guard: none(ServiceClient is documented
        # single-thread-per-call; counters ride the caller's thread)
        self.bytes_recv = 0  # guard: none(see bytes_sent)
        self._batch_ops: dict[Any, list] = {}  # guard: none(touched
        # only inside _send/_recv_for on the caller's thread, same as
        # _pending/_replies above)
        if negotiate is None:
            negotiate = env.env_flag("SIEVE_WIRE_V2", True)
        if negotiate:
            self._negotiate()

    def _negotiate(self) -> None:
        """The wire hello: offer ``SUPPORTED_WIRE``, adopt the server's
        pick. A v1-only peer answers ``wire: 1`` (or, pre-negotiation
        builds, a typed bad_request) — either way the client stays on
        JSON and logs ONE ``wire_downgrade`` event (+ counter), so a
        silently degraded fleet is visible in metrics (ISSUE 16)."""
        try:
            reply = self._call({"type": "hello",
                                "wire": list(SUPPORTED_WIRE)})
        except (CallTimeout, ConnectionError, OSError):
            # a connection dying under the hello is an outage, not a
            # protocol downgrade: close it and let the FIRST REAL CALL
            # raise the ConnectionError — the exact place a
            # pre-negotiation client would have surfaced it (the
            # constructor itself never sent anything back then)
            self.close()
            return
        if reply.get("type") == "hello" and reply.get("ok"):
            try:
                self.wire_v = int(reply.get("wire") or WIRE_V1)
            except (TypeError, ValueError):
                self.wire_v = WIRE_V1
        if self.wire_v < WIRE_V2:
            self.downgraded = True
            _emit_wire_downgrade(self._addr, self.wire_v)

    def close(self) -> None:
        self._dead = True
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ServiceClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --- raw -------------------------------------------------------------

    def _send(self, msg: dict):
        """Ship one message without waiting; returns its wire id."""
        if self._dead:
            raise ConnectionError(
                "connection closed (earlier timeout desynced the reply "
                "stream); open a new client"
            )
        rid = msg.setdefault("id", next(self._ids))
        frame = None
        if (self.wire_v >= WIRE_V2 and msg.get("op") == "batch"
                and "items" in msg):
            packed = batch_items_to_cols(msg["items"])
            if packed is not None:
                cols, ops = packed
                header = {k: v for k, v in msg.items() if k != "items"}
                frame = encode_msg_v2(header, cols)
                self._batch_ops[rid] = ops
        if frame is None:
            frame = encode_msg(msg)
        self._sock.sendall(frame)
        self.bytes_sent += len(frame)
        self._pending[rid] = trace.now_s()
        return rid

    def _recv_for(self, rid) -> dict:
        """Block until the reply for ``rid`` arrives. Replies come in
        COMPLETION order; ones for other outstanding ids are stashed
        and handed out when their id is asked for."""
        if rid in self._replies:
            self._pending.pop(rid, None)
            return self._replies.pop(rid)
        while True:
            try:
                reply = self._recv()
            except socket.timeout:
                # requests are still in flight server-side: a later recv
                # on this socket would read THEIR replies as its own —
                # close it (every stashed reply already collected stays
                # valid; everything still pending is lost)
                self.close()
                raise CallTimeout(
                    f"no reply within {self._sock.gettimeout()}s; "
                    "connection closed (request outcome unknown)"
                ) from None
            if reply is None:
                raise ConnectionError("service closed the connection")
            got = reply.get("id")
            self._rehydrate(got, reply)
            if got == rid:
                self._pending.pop(rid, None)
                return reply
            self._replies[got] = reply

    def _recv(self) -> dict | None:
        """One frame off the socket, counted into ``bytes_recv``."""
        header = _recv_exact(self._sock, 8)
        if header is None:
            return None
        (length,) = struct.unpack(">Q", header)
        blob = _recv_exact(self._sock, length)
        if blob is None:
            return None
        self.bytes_recv += 8 + length
        return decode_body(blob)

    def _rehydrate(self, rid, reply: dict) -> None:
        """Rebuild the v1-shaped ``value`` from a v2 columnar reply, in
        place — callers above this point never see columns. A JSON
        reply (including a whole-batch error for a columnar request)
        passes through untouched."""
        if "_cols" not in reply:
            self._batch_ops.pop(rid, None)
            return
        del reply["_cols"]
        vkind = reply.pop("vkind", None)
        if vkind == "batch":
            reply["value"] = batch_reply_value(
                reply, self._batch_ops.pop(rid, None)
            )
        elif vkind == "primes":
            reply["value"] = primes_reply_value(
                reply, as_array=self.keep_arrays
            )

    def _call(self, msg: dict) -> dict:
        return self._recv_for(self._send(msg))

    def query(self, op: str, deadline_s: float | None = None,
              **params: Any) -> dict:
        """One query; returns the raw reply dict (ok or typed error)."""
        msg: dict[str, Any] = {"type": "query", "op": op, **params}
        if deadline_s is not None:
            msg["deadline_s"] = deadline_s
        # trace ctx (ISSUE 12): a caller-supplied ctx (the router
        # forwarding its route context) wins; a bare client is attempt 0
        msg.setdefault("ctx", f"{self._run_id}/{next(self._ctx_seq)}.0")
        msg.setdefault("t_send", round(trace.now_s(), 6))
        return self._call(msg)

    # --- pipelining (ISSUE 14) -------------------------------------------

    def submit(self, op: str, deadline_s: float | None = None,
               **params: Any):
        """Send one query WITHOUT waiting for its reply; returns the
        wire id to pass to :meth:`drain`. Any number may be in flight."""
        msg: dict[str, Any] = {"type": "query", "op": op, **params}
        if deadline_s is not None:
            msg["deadline_s"] = deadline_s
        msg.setdefault("ctx", f"{self._run_id}/{next(self._ctx_seq)}.0")
        msg.setdefault("t_send", round(trace.now_s(), 6))
        return self._send(msg)

    def drain(self, ids: Sequence | None = None) -> dict:
        """Collect replies for ``ids`` (default: every outstanding
        submit), keyed by wire id. Blocks until each asked-for reply
        has arrived; replies for ids NOT asked for stay stashed."""
        if ids is None:
            ids = list(self._pending)
        return {rid: self._recv_for(rid) for rid in ids}

    def pending(self) -> int:
        """Submitted requests whose replies have not been collected."""
        return len(self._pending)

    def query_batch(self, items: Sequence[dict],
                    deadline_s: float | None = None) -> list[dict]:
        """One ``batch`` RPC carrying M member queries; returns M typed
        per-member outcomes (``{"ok": True, "value": ...}`` or
        ``{"ok": False, "error": kind, ...}``), in member order. Raises
        :class:`ServiceError` only for whole-batch failures (malformed
        items container, oversized batch)."""
        return self._value(self.query("batch", deadline_s,
                                      items=list(items)))

    def _value(self, reply: dict):
        if reply.get("ok"):
            return reply["value"]
        raise ServiceError(
            reply.get("error", "internal"),
            reply.get("detail", ""),
            reply.get("partial"),
            shard=reply.get("shard"),
        )

    # --- ops -------------------------------------------------------------

    def pi(self, x: int, deadline_s: float | None = None) -> int:
        return self._value(self.query("pi", deadline_s, x=x))

    def is_prime(self, x: int, deadline_s: float | None = None) -> bool:
        return bool(self._value(self.query("is_prime", deadline_s, x=x)))

    def count(self, lo: int, hi: int, kind: str = "primes",
              deadline_s: float | None = None) -> int:
        return self._value(
            self.query("count", deadline_s, lo=lo, hi=hi, kind=kind)
        )

    def nth_prime(self, k: int, deadline_s: float | None = None) -> int:
        return self._value(self.query("nth_prime", deadline_s, k=k))

    def primes(self, lo: int, hi: int,
               deadline_s: float | None = None) -> list[int]:
        return self._value(self.query("primes", deadline_s, lo=lo, hi=hi))

    # --- control plane ---------------------------------------------------

    def health(self) -> dict:
        return self._call({"type": "health"})

    def stats(self) -> dict:
        return self._call({"type": "stats"})["stats"]

    def shutdown(self) -> dict:
        """Ask the server to drain (the wire twin of SIGTERM)."""
        return self._call({"type": "shutdown"})

    def metrics(self) -> dict:
        """Full metrics-registry snapshot (ISSUE 12 live telemetry op)."""
        return self._call({"type": "metrics"})["metrics"]

    def debug(self) -> dict | None:
        """Inline flight-recorder bundle (ISSUE 13 postmortem op).

        Answered by the reader thread like ``metrics``, so it works
        against a server whose worker pool is wedged. None when the
        endpoint runs with the recorder disabled."""
        return self._call({"type": "debug"})["bundle"]

    def profile(self) -> dict | None:
        """Continuous-profiler snapshot (ISSUE 20 flame-pull op).

        The endpoint's collapsed-stack table — every sample tagged with
        its thread role and active span — answered inline like ``debug``
        so a wedged worker pool still profiles. None when the endpoint
        runs with the sampler disabled (SIEVE_PROF_HZ=0)."""
        return self._call({"type": "profile"})["profile"]

    def exemplars(self, ctx: str | None = None,
                  n: int | None = None) -> list[dict]:
        """Kept tail-sampled exemplars (ISSUE 19), newest last.

        Served inline from the endpoint's in-memory ring; ``ctx`` is a
        trace-context prefix filter (how the router pulls the downstream
        exemplars of one slow route), ``n`` caps the count. Empty when
        the endpoint runs with exemplar sampling disabled."""
        msg: dict = {"type": "exemplars"}
        if ctx is not None:
            msg["ctx"] = ctx
        if n is not None:
            msg["n"] = n
        return self._call(msg)["exemplars"]

    def inject_chaos(self, spec: str) -> dict:
        return self._call({"type": "chaos", "spec": spec})


class ClientPool:
    """One pipelined :class:`ServiceClient` per address, reused across
    calls (ISSUE 14). tools/fleet_top.py and tools/fleet_debug.py poll
    every endpoint once per refresh cycle; before the pool each poll
    opened (and tore down) a fresh TCP connection per target. The pool
    hands back the cached client until a transport failure invalidates
    it, and counts reconnects so the reuse is provable in tests."""

    def __init__(self, timeout_s: float = 5.0):
        self.timeout_s = timeout_s
        self._clients: dict[str, ServiceClient] = {}
        self._ever: set[str] = set()
        self._lock = named_lock("ClientPool._lock")
        self.connects = 0    # guard: _lock
        self.reconnects = 0  # guard: _lock

    def get(self, addr: str) -> ServiceClient:
        """Cached client for ``addr``; (re)connects only when there is
        none or the cached one is dead. A re-connection to an address
        seen before counts as a reconnect."""
        with self._lock:
            cli = self._clients.get(addr)
            if cli is not None and not cli._dead:
                return cli
            cli = ServiceClient(addr, timeout_s=self.timeout_s)
            self._clients[addr] = cli
            self.connects += 1
            if addr in self._ever:
                self.reconnects += 1
            self._ever.add(addr)
            return cli

    def invalidate(self, addr: str) -> None:
        """Drop the cached client after a transport failure; the next
        :meth:`get` reconnects (and counts it)."""
        with self._lock:
            cli = self._clients.pop(addr, None)
        if cli is not None:
            cli.close()

    def close(self) -> None:
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for cli in clients:
            cli.close()

    def __enter__(self) -> "ClientPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# --- replica failover --------------------------------------------------------

# typed error kinds that justify trying another replica: the condition is
# local to the replica (its queue, its backend, its lifecycle), so a
# sibling may well answer. bad_request would fail identically everywhere;
# deadline_exceeded already spent the caller's budget.
FAILOVER_KINDS = frozenset({"overloaded", "degraded", "draining"})


class _Replica:
    """One address + its connection and circuit state. ``lock`` guards
    the connection: one THREAD at a time drives it, though that thread
    may pipeline any number of requests (ISSUE 14)."""

    __slots__ = ("addr", "client", "lock", "fails", "open_until", "probed")

    def __init__(self, addr: str):
        self.addr = addr
        self.client: ServiceClient | None = None
        self.lock = named_lock("_Replica.lock")
        self.fails = 0
        self.open_until = 0.0
        # monotonic timestamp of the last successful health probe
        # (0.0 = never / invalidated by _mark_down)
        self.probed = 0.0


class ReplicaSet:
    """Failover client over N replica addresses (see module docstring).

    Thread-safe: the set-level lock covers selection and circuit state;
    each replica's lock serializes its connection. ``rounds`` full passes
    over the replica list are attempted, with the PR 6 capped-exponential
    + jitter backoff between passes, before giving up.
    """

    def __init__(
        self,
        addrs: Sequence[str],
        timeout_s: float = 60.0,
        probe_timeout_s: float = 2.0,
        rounds: int = 3,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 1.0,
        circuit_cooldown_s: float = 1.0,
        probe_ttl_s: float | None = None,
        negotiate: bool | None = None,
        keep_arrays: bool = False,
    ):
        if not addrs:
            raise ValueError("ReplicaSet needs at least one address")
        self._replicas = [_Replica(a) for a in addrs]
        self.timeout_s = timeout_s
        self.probe_timeout_s = probe_timeout_s
        self.rounds = rounds
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.circuit_cooldown_s = circuit_cooldown_s
        # probe freshness (ISSUE 11): None keeps the legacy contract — a
        # replica probes once and stays trusted until marked down. The
        # router passes a short TTL so per-request shard selection never
        # adds a probe round-trip on the hot path yet still re-detects
        # draining replicas within one TTL.
        self.probe_ttl_s = probe_ttl_s
        self._lock = named_lock("ReplicaSet._lock")
        self._rr = 0
        self._run_id = uuid.uuid4().hex[:8]
        self._ctx_seq = itertools.count(1)
        # observability for tools/tests: how often selection failed over
        self.failovers = 0
        self.probes = 0
        # wire v2 (ISSUE 16): per-connection negotiation preference
        # (None = SIEVE_WIRE_V2 env default), array pass-through for
        # the router's shard legs, and how many fresh connections came
        # up downgraded to v1 JSON (surfaced in router stats)
        self.negotiate = negotiate
        self.keep_arrays = keep_arrays
        self.downgrades = 0

    def _connect(self, addr: str) -> ServiceClient:
        cli = ServiceClient(addr, timeout_s=self.timeout_s,
                            negotiate=self.negotiate,
                            keep_arrays=self.keep_arrays)
        if cli.downgraded:
            with self._lock:
                self.downgrades += 1
        return cli

    def close(self) -> None:
        for rep in self._replicas:
            with rep.lock:
                if rep.client is not None:
                    rep.client.close()
                    rep.client = None

    def __enter__(self) -> "ReplicaSet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --- selection & circuit ---------------------------------------------

    def _candidates(self) -> list[_Replica]:
        """Replicas in try-order: round-robin rotation, circuit-closed
        first; open-but-expired (half-open) after; still-open last — a
        fully broken set must still attempt *something* each round."""
        now = time.monotonic()
        with self._lock:
            order = (self._replicas[self._rr:] + self._replicas[: self._rr])
            self._rr = (self._rr + 1) % len(self._replicas)
        closed = [r for r in order if r.fails == 0]
        half = [r for r in order if r.fails > 0 and now >= r.open_until]
        still = [r for r in order if r.fails > 0 and now < r.open_until]
        return closed + half + still

    def _mark_down(self, rep: _Replica) -> None:
        with self._lock:
            rep.fails += 1
            cooldown = min(
                self.backoff_cap_s * 8,
                self.circuit_cooldown_s * (2 ** min(rep.fails - 1, 6)),
            )
            rep.open_until = time.monotonic() + cooldown
            rep.probed = 0.0
        with rep.lock:
            if rep.client is not None:
                rep.client.close()
                rep.client = None

    def _mark_up(self, rep: _Replica) -> None:
        with self._lock:
            rep.fails = 0
            rep.open_until = 0.0

    def _probe_fresh(self, rep: _Replica, now: float) -> bool:
        if rep.probed <= 0.0:
            return False
        if self.probe_ttl_s is None:  # legacy: trusted until marked down
            return True
        return now - rep.probed <= self.probe_ttl_s

    def _ensure_client(self, rep: _Replica) -> ServiceClient:
        """Connect + health-probe (caller holds rep.lock). A replica that
        was marked down — or never used — must prove itself with a probe
        before it gets real queries; a draining replica fails the probe
        so rolling restarts steer new work away without a single typed
        ``draining`` round-trip wasted. With ``probe_ttl_s`` set, a probe
        stays trusted for that window — the counters make the cache
        provable (``router.probe_cached`` vs ``router.probe_sent``)."""
        if rep.client is None:
            rep.client = self._connect(rep.addr)
            rep.probed = 0.0
        now = time.monotonic()
        if self._probe_fresh(rep, now):
            registry().counter("router.probe_cached").inc()
            return rep.client
        registry().counter("router.probe_sent").inc()
        rep.client._sock.settimeout(self.probe_timeout_s)
        try:
            health = rep.client.health()
        finally:
            rep.client._sock.settimeout(self.timeout_s)
        with self._lock:
            self.probes += 1
        if health.get("draining"):
            raise ServiceError("draining", f"{rep.addr} is draining")
        rep.probed = time.monotonic()
        return rep.client

    # --- calls ------------------------------------------------------------

    def query(self, op: str, deadline_s: float | None = None, *,
              ctx: str | None = None, telemetry: bool = False,
              **params: Any) -> dict:
        """One query with failover; returns the raw reply dict. Raises
        ConnectionError-shaped failures only as a final
        ``ServiceError("unavailable")`` after every replica and round is
        exhausted; a non-failover typed error returns immediately.

        ``ctx`` is the trace-context BASE (``run_id/<seq>``, minted here
        when absent — the router passes its route context down); each
        try gets a fresh ``.{try}`` attempt suffix so retried requests
        never alias spans. ``telemetry=True`` asks the replica to
        piggyback its span ring on the reply."""
        msg: dict[str, Any] = {"type": "query", "op": op, **params}
        if deadline_s is not None:
            msg["deadline_s"] = deadline_s
        if ctx is None:
            ctx = f"{self._run_id}/{next(self._ctx_seq)}"
        last_typed: dict | None = None
        last_err: Exception | None = None
        tries = 0
        for attempt in range(1, self.rounds + 1):
            for i, rep in enumerate(self._candidates()):
                if i > 0:
                    with self._lock:
                        self.failovers += 1
                try:
                    with rep.lock:
                        client = self._ensure_client(rep)
                        # fresh copy per attempt: ids are per-connection,
                        # a retried dict must not pin a stale one, and
                        # the trace ctx names THIS attempt
                        attempt_msg = dict(msg)
                        attempt_msg["ctx"] = f"{ctx}.{tries}"
                        t_send = round(trace.now_s(), 6)
                        attempt_msg["t_send"] = t_send
                        if telemetry:
                            attempt_msg["telemetry"] = True
                        tries += 1
                        reply = client._call(attempt_msg)
                except (ConnectionError, OSError, CallTimeout) as e:
                    self._mark_down(rep)
                    last_err = e
                    continue
                except ServiceError as e:  # probe said draining
                    self._mark_down(rep)
                    last_typed = {"ok": False, "error": e.kind,
                                  "detail": e.detail, "op": op}
                    continue
                # clock-probe annotation for the caller's aligner: which
                # replica answered, bracketed by our send/done timestamps
                reply["probe"] = {
                    "addr": rep.addr,
                    "t_send": t_send,
                    "t_done": round(trace.now_s(), 6),
                }
                self._mark_up(rep)
                if reply.get("ok") or reply.get("error") not in FAILOVER_KINDS:
                    return reply
                last_typed = reply  # overloaded/degraded/draining: next
            if attempt < self.rounds:
                # PR 6 backoff shape: capped exponential, full jitter
                delay = min(self.backoff_cap_s,
                            self.backoff_base_s * (2 ** (attempt - 1)))
                time.sleep(delay * (0.5 + random.random()))
        if last_typed is not None:
            return last_typed
        raise ServiceError(
            "unavailable",
            f"no replica answered after {self.rounds} rounds over "
            f"{len(self._replicas)} replicas (last: {last_err!r})",
        )

    def query_batch(self, items: Sequence[dict],
                    deadline_s: float | None = None, *,
                    ctx: str | None = None,
                    telemetry: bool = False) -> list[dict]:
        """One ``batch`` RPC with whole-batch failover: the standard
        :meth:`query` retry policy applies to the RPC itself (a member's
        typed outcome is the SERVER's answer and is never retried
        here — per-member semantics live inside the batch reply)."""
        return self._value(self.query("batch", deadline_s, ctx=ctx,
                                      telemetry=telemetry,
                                      items=list(items)))

    def query_many(self, requests: Sequence[dict],
                   deadline_s: float | None = None, *,
                   ctx: str | None = None,
                   window: int | None = None) -> list[dict]:
        """Pipeline N independent queries with failover; returns one raw
        reply dict per request, in REQUEST order.

        Every still-unanswered request rides ONE pipelined connection
        (at most ``window`` in flight when set), drained in send order.
        A transport failure mid-pipeline marks that replica down and
        retries ONLY the unanswered suffix on the next candidate —
        replies already collected are kept, the suffix gets fresh
        attempt contexts. A typed FAILOVER_KINDS reply retries just
        that member; other typed replies (bad_request,
        deadline_exceeded) are final. Members no replica ever answered
        come back as synthesized ``unavailable`` replies (or their last
        failover-kind reply), so positions are stable and the set never
        invents an answer."""
        n = len(requests)
        results: list[dict | None] = [None] * n
        typed: dict[int, dict] = {}
        if ctx is None:
            ctx = f"{self._run_id}/{next(self._ctx_seq)}"
        last_err: Exception | None = None
        tries = 0
        for attempt in range(1, self.rounds + 1):
            for i_rep, rep in enumerate(self._candidates()):
                todo = [i for i in range(n) if results[i] is None]
                if not todo:
                    return results
                if i_rep > 0:
                    with self._lock:
                        self.failovers += 1
                tries += 1
                try:
                    with rep.lock:
                        client = self._ensure_client(rep)
                        cap = window if window and window > 0 else len(todo)
                        inflight: list[tuple[int, Any, float]] = []
                        qi = 0
                        while qi < len(todo) or inflight:
                            while qi < len(todo) and len(inflight) < cap:
                                i = todo[qi]
                                qi += 1
                                msg = dict(requests[i])
                                msg["type"] = "query"
                                msg.pop("id", None)  # ids are per-conn
                                if (deadline_s is not None
                                        and "deadline_s" not in msg):
                                    msg["deadline_s"] = deadline_s
                                msg["ctx"] = f"{ctx}.{tries}:{i}"
                                t_send = round(trace.now_s(), 6)
                                msg["t_send"] = t_send
                                inflight.append(
                                    (i, client._send(msg), t_send)
                                )
                            i, rid, t_send = inflight.pop(0)
                            reply = client._recv_for(rid)
                            reply["probe"] = {
                                "addr": rep.addr,
                                "t_send": t_send,
                                "t_done": round(trace.now_s(), 6),
                            }
                            if (reply.get("ok")
                                    or reply.get("error")
                                    not in FAILOVER_KINDS):
                                results[i] = reply
                            else:
                                typed[i] = reply  # retry on next replica
                except (ConnectionError, OSError, CallTimeout) as e:
                    self._mark_down(rep)
                    last_err = e
                    continue
                except ServiceError as e:  # probe said draining
                    self._mark_down(rep)
                    for i in todo:
                        typed.setdefault(i, {
                            "ok": False, "error": e.kind,
                            "detail": e.detail,
                            "op": str(requests[i].get("op", "")),
                        })
                    continue
                self._mark_up(rep)
            if (attempt < self.rounds
                    and any(r is None for r in results)):
                delay = min(self.backoff_cap_s,
                            self.backoff_base_s * (2 ** (attempt - 1)))
                time.sleep(delay * (0.5 + random.random()))
        for i in range(n):
            if results[i] is None:
                results[i] = typed.get(i) or {
                    "ok": False,
                    "op": str(requests[i].get("op", "")),
                    "error": "unavailable",
                    "detail": f"no replica answered after {self.rounds} "
                              f"rounds over {len(self._replicas)} "
                              f"replicas (last: {last_err!r})",
                }
        return results

    def health(self) -> dict:
        """Health of the first reachable replica (no probe gate: a
        draining replica's health is exactly what the caller wants to
        see). Used by the router to aggregate per-shard health."""
        last_err: Exception | None = None
        for rep in self._candidates():
            try:
                with rep.lock:
                    if rep.client is None:
                        rep.client = self._connect(rep.addr)
                    rep.client._sock.settimeout(self.probe_timeout_s)
                    try:
                        return rep.client.health()
                    finally:
                        if rep.client is not None:
                            rep.client._sock.settimeout(self.timeout_s)
            except (ConnectionError, OSError, CallTimeout) as e:
                self._mark_down(rep)
                last_err = e
        raise ServiceError(
            "unavailable",
            f"no replica health over {len(self._replicas)} replicas "
            f"(last: {last_err!r})",
        )

    def metrics(self) -> dict:
        """Metrics snapshot of the first reachable replica (the fleet
        poller asks each replica directly; this is the failover twin)."""
        last_err: Exception | None = None
        for rep in self._candidates():
            try:
                with rep.lock:
                    if rep.client is None:
                        rep.client = self._connect(rep.addr)
                    return rep.client.metrics()
            except (ConnectionError, OSError, CallTimeout) as e:
                self._mark_down(rep)
                last_err = e
        raise ServiceError(
            "unavailable",
            f"no replica metrics over {len(self._replicas)} replicas "
            f"(last: {last_err!r})",
        )

    def telemetry_flush(self) -> list[dict]:
        """Pull the residual span ring from EVERY reachable replica.

        The batched piggyback leaves up to ``telemetry_batch - 1``
        events sitting in each replica's ring; the router calls this
        when its trace closes so the span tail still lands in the
        merged file. Every replica is visited (not first-reachable —
        each holds distinct spans); unreachable ones are skipped, and
        each reply is probe-annotated for the caller's clock aligner.
        """
        replies: list[dict] = []
        for rep in self._replicas:
            try:
                with rep.lock:
                    if rep.client is None:
                        rep.client = self._connect(rep.addr)
                    t_send = round(trace.now_s(), 6)
                    reply = rep.client._call(
                        {"type": "telemetry", "t_send": t_send}
                    )
                    reply["probe"] = {
                        "addr": rep.addr,
                        "t_send": t_send,
                        "t_done": round(trace.now_s(), 6),
                    }
                    replies.append(reply)
            except (ConnectionError, OSError, CallTimeout):
                self._mark_down(rep)
        return replies

    def exemplars(self, ctx: str | None = None) -> list[dict]:
        """Kept exemplars from EVERY reachable replica (ISSUE 19).

        Every replica is visited, not first-reachable — a routed
        request's downstream query ran on exactly one of them, and the
        caller does not know which. Each record is tagged with the
        replica address it came from; unreachable replicas are skipped
        (a down replica must not fail the pull that is trying to
        explain why a route was slow). A failed pull only drops the
        cached connection — it never marks the replica down: the
        observability plane must not mutate routing state, or a
        monitoring sweep would pre-empt (and hide) the query path's own
        failover accounting."""
        out: list[dict] = []
        msg: dict = {"type": "exemplars"}
        if ctx is not None:
            msg["ctx"] = ctx
        for rep in self._replicas:
            try:
                with rep.lock:
                    if rep.client is None:
                        rep.client = self._connect(rep.addr)
                    reply = rep.client._call(dict(msg))
                for rec in reply.get("exemplars") or []:
                    rec["addr"] = rep.addr
                    out.append(rec)
            except (ConnectionError, OSError, CallTimeout):
                with rep.lock:
                    if rep.client is not None:
                        rep.client.close()
                        rep.client = None
        return out

    def _value(self, reply: dict):
        if reply.get("ok"):
            return reply["value"]
        raise ServiceError(
            reply.get("error", "internal"),
            reply.get("detail", ""),
            reply.get("partial"),
            shard=reply.get("shard"),
        )

    # --- ops (same surface as ServiceClient) ------------------------------

    def pi(self, x: int, deadline_s: float | None = None) -> int:
        return self._value(self.query("pi", deadline_s, x=x))

    def is_prime(self, x: int, deadline_s: float | None = None) -> bool:
        return bool(self._value(self.query("is_prime", deadline_s, x=x)))

    def count(self, lo: int, hi: int, kind: str = "primes",
              deadline_s: float | None = None) -> int:
        return self._value(
            self.query("count", deadline_s, lo=lo, hi=hi, kind=kind)
        )

    def nth_prime(self, k: int, deadline_s: float | None = None) -> int:
        return self._value(self.query("nth_prime", deadline_s, k=k))

    def primes(self, lo: int, hi: int,
               deadline_s: float | None = None) -> list[int]:
        return self._value(self.query("primes", deadline_s, lo=lo, hi=hi))
