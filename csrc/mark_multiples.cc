// Native CPU mark_multiples: the segmented-sieve hot loop in C++.
//
// SURVEY.md section 2 ("CPU marking kernel (native)"): a word-wise strided
// bit-clear over a packed uint64 segment, popcount via
// __builtin_popcountll. The interface is the same packing-agnostic marking
// spec used by the device kernels (sieve/kernels/specs.py): spec (m, r, s)
// clears flag bits {b : b == s (mod m), b >= s}, which every packing's
// composite-marking reduces to. Exposed via a C ABI for ctypes
// (pybind11 is not available in this image).

#include <cstdint>
#include <cstring>

extern "C" {

// Initialize a segment: all candidate flags set, tail bits beyond nbits 0.
void sieve_init(uint64_t* words, int64_t nwords, int64_t nbits) {
  memset(words, 0xFF, static_cast<size_t>(nwords) * 8);
  int64_t tail = nbits & 63;
  int64_t full = nbits >> 6;
  if (tail) {
    words[full] &= (1ULL << tail) - 1;
    ++full;
  }
  for (int64_t w = full; w < nwords; ++w) words[w] = 0;
}

// The hot loop: strided composite-marking for every spec.
void mark_multiples(uint64_t* words, int64_t nbits, const int64_t* m,
                    const int64_t* s, int64_t nspecs) {
  for (int64_t i = 0; i < nspecs; ++i) {
    const int64_t stride = m[i];
    for (int64_t b = s[i]; b < nbits; b += stride) {
      words[b >> 6] &= ~(1ULL << (b & 63));
    }
  }
}

int64_t popcount_words(const uint64_t* words, int64_t nwords) {
  int64_t total = 0;
  for (int64_t w = 0; w < nwords; ++w) {
    total += __builtin_popcountll(words[w]);
  }
  return total;
}

// Twin pairs (b, b+shift) with both flags set, left member's position
// allowed by pair_mask (a 64-bit mask whose period-8 pattern encodes the
// wheel30 pairable residue classes; all-ones for plain/odds). Tail bits
// beyond nbits are already 0, so out-of-range pairs self-exclude.
int64_t twin_count(const uint64_t* words, int64_t nwords, int shift,
                   uint64_t pair_mask) {
  int64_t total = 0;
  for (int64_t w = 0; w < nwords; ++w) {
    uint64_t right = words[w] >> shift;
    if (w + 1 < nwords) {
      right |= words[w + 1] << (64 - shift);
    }
    total += __builtin_popcountll(words[w] & right & pair_mask);
  }
  return total;
}

}  // extern "C"
