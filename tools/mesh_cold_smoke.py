"""Mesh cold-plane smoke: one SPMD drain vs K sequential markings
(ISSUE 18 acceptance; tier-1 via tests/test_mesh_cold.py).

Forces an 8-device virtual CPU mesh (``XLA_FLAGS`` before jax imports),
then drives the ``MeshWorker`` through the two claims the issue makes:

1. parity — a chunk grid per packing (plain / odds / wheel30, twins on
   and off) that includes a sub-word sliver (CPU-fallback path) and a
   deliberately non-power-of-two, non-multiple-of-ndev chunk count (pad
   rows + masking exercised on every launch). Every ``MeshWorker``
   result must match the ``CpuNumpyWorker`` reference field-by-field,
   and every prime count must also match a direct numpy segmented sieve
   built here from the seed primes — two independent oracles, so a
   wrong mesh launch cannot hide behind a shared bug.
2. throughput — the bench half (``service_cold_drain_throughput``):
   values/s through one drain slice of equal-span cold chunks, mesh
   (ONE ``shard_map`` launch for the lot) vs loop (the classic
   ``process_segment``-per-chunk JaxWorker path the service's loop
   backend runs). Both sides are warmed, parity-asserted against each
   other, and the launch counter must show exactly one mesh dispatch
   per drain. The JSON line feeds ``bench.py`` /
   ``tools/bench_compare.py`` (unit ``cold_throughput``, gated against
   drops); ``vs_baseline`` is the mesh/loop speedup.

Exit status: 0 on full parity (MESH_COLD_SMOKE_OK), 1 on any violation.

Usage: python tools/mesh_cold_smoke.py [--chunks K] [--span BITS]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# the mesh needs its devices BEFORE jax initializes: force the 8-way
# virtual CPU host unless the caller already forced a device count
if "--xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "SIEVE_JAX_PLATFORM", os.environ["JAX_PLATFORMS"].split(",")[0]
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from sieve.backends.cpu_numpy import CpuNumpyWorker  # noqa: E402
from sieve.backends.mesh_backend import MeshWorker  # noqa: E402
from sieve.config import SieveConfig  # noqa: E402
from sieve.seed import seed_primes  # noqa: E402


def fail(msg: str) -> None:
    print(f"FAIL: {msg}", flush=True)
    sys.exit(1)


def oracle_count(lo: int, hi: int, seeds: np.ndarray) -> int:
    """Independent prime count for [lo, hi): direct numpy segmented
    sieve from the seed primes — no sieve/ marking code involved."""
    is_p = np.ones(hi - lo, dtype=bool)
    for v in range(lo, min(hi, 2)):
        is_p[v - lo] = False
    for p in seeds:
        p = int(p)
        if p * p >= hi:
            break
        start = max(p * p, ((lo + p - 1) // p) * p)
        is_p[start - lo:: p] = False
    return int(is_p.sum())


def _cfg(packing: str, twins: bool, n: int) -> SieveConfig:
    return SieveConfig(
        n=n, backend="cpu-numpy", packing=packing, twins=twins,
        n_segments=1, quiet=True,
    )


# parity grid: a sub-word sliver (CPU fallback), word-unaligned spans,
# and 5 equal-span chunks — not a multiple of 8 devices and not a power
# of two, so every launch pads rows and must mask them out exactly
PARITY_SEGS = [
    (2, 40),
    (1_000, 9_000),
    (9_000, 17_192),
    (60_000, 68_192),
    (68_192, 76_384),
]


def parity_check() -> None:
    hi_max = max(hi for _, hi in PARITY_SEGS)
    seeds = seed_primes(int(hi_max ** 0.5) + 1)
    for packing in ("plain", "odds", "wheel30"):
        for twins in (False, True):
            cfg = _cfg(packing, twins, hi_max)
            mesh = MeshWorker(cfg)
            ref = CpuNumpyWorker(cfg)
            got = mesh.process_segments(PARITY_SEGS, seeds)
            for i, (lo, hi) in enumerate(PARITY_SEGS):
                want = ref.process_segment(lo, hi, seeds, i)
                for f in ("seg_id", "lo", "hi", "count", "twin_count",
                          "first_word", "last_word", "nbits"):
                    g, w = getattr(got[i], f), getattr(want, f)
                    if g != w:
                        fail(
                            f"parity {packing}/twins={twins} "
                            f"[{lo},{hi}) field {f}: mesh={g} cpu={w}"
                        )
                oc = oracle_count(lo, hi, seeds)
                if got[i].count != oc:
                    fail(
                        f"oracle {packing}/twins={twins} [{lo},{hi}): "
                        f"mesh count={got[i].count} oracle={oc}"
                    )
            if mesh.launches < 1:
                fail(f"parity {packing}: no mesh launches recorded")
            mesh.close()
            ref.close()
    print("parity: plain/odds/wheel30 x twins on/off exact "
          "(mesh vs cpu-numpy vs direct oracle)", flush=True)


def throughput(chunks: int, span_bits: int) -> dict:
    span = 1 << span_bits
    lo0 = 10_000_000
    segs = [(lo0 + i * span, lo0 + (i + 1) * span) for i in range(chunks)]
    hi_max = segs[-1][1]
    seeds = seed_primes(int(hi_max ** 0.5) + 1)
    cfg = _cfg("odds", False, hi_max)

    mesh = MeshWorker(cfg)
    mesh.process_segments(segs, seeds)  # warm: compile + prepare cache
    launches0 = mesh.launches
    t0 = time.perf_counter()
    mesh_res = mesh.process_segments(segs, seeds)
    mesh_s = time.perf_counter() - t0
    drain_launches = mesh.launches - launches0
    if drain_launches != 1:
        fail(
            f"one drain of {chunks} equal-span chunks took "
            f"{drain_launches} SPMD launches (want exactly 1)"
        )

    # the loop alternative the service's --cold-backend loop runs: the
    # same jax kernel, one process_segment launch per chunk
    from sieve.backends.jax_backend import JaxWorker

    loop = JaxWorker(cfg)
    for i, (lo, hi) in enumerate(segs):  # warm
        loop.process_segment(lo, hi, seeds, i)
    t0 = time.perf_counter()
    loop_res = [
        loop.process_segment(lo, hi, seeds, i)
        for i, (lo, hi) in enumerate(segs)
    ]
    loop_s = time.perf_counter() - t0

    for m, l_ in zip(mesh_res, loop_res):
        if (m.count, m.first_word, m.last_word) != (
            l_.count, l_.first_word, l_.last_word
        ):
            fail(f"mesh vs loop drift at [{m.lo},{m.hi})")
    values = chunks * span
    out = {
        "metric": "service_cold_drain_throughput",
        "value": round(values / mesh_s, 1),
        "unit": "cold_throughput",
        "vs_baseline": round(loop_s / mesh_s, 3),
        "loop_values_per_sec": round(values / loop_s, 1),
        "chunks": chunks,
        "devices": mesh.devices,
        "spmd_launches": drain_launches,
    }
    mesh.close()
    loop.close()
    return out


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--chunks", type=int, default=16,
                   help="cold chunks per drain slice (default 16)")
    p.add_argument("--span", type=int, default=16,
                   help="log2 chunk span (default 16 -> 65536 values)")
    args = p.parse_args(argv)
    parity_check()
    line = throughput(args.chunks, args.span)
    print(json.dumps(line), flush=True)
    print("MESH_COLD_SMOKE_OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
