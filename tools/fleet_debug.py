"""Fleet-wide postmortem collection: pull every process's inline
flight-recorder bundle into ONE merged fleet bundle (ISSUE 13).

Asks the router for its ``debug`` wire op (answered inline by the
reader thread, so a wedged worker pool still dumps), reads the shard
replica addresses out of the router's health reply, pulls each
replica's bundle the same way, and writes the merged document as
``fleet_bundle.json`` under ``--out``. Render it with::

    python tools/trace_report.py <out>/fleet_bundle.json --bundle

Exit 1 when the router is unreachable or any advertised replica failed
to hand over a bundle — a partial postmortem is still written (each
missing process carries its named error), but scripts must see the gap.

Usage:
    python tools/fleet_debug.py 127.0.0.1:7733 [--out DIR] [--timeout S]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sieve.debug import FLEET_BUNDLE_VERSION  # noqa: E402
from sieve.service.client import ClientPool, ServiceClient  # noqa: E402

FLEET_BUNDLE_FILE = "fleet_bundle.json"


def _pull(addr: str, timeout_s: float,
          pool: ClientPool | None = None) -> dict[str, Any]:
    """One endpoint's health + inline debug bundle, or a named error.

    With a ``pool`` (ISSUE 14) the endpoint's pipelined connection is
    reused across calls; a transport failure invalidates just that
    entry (counted in ``pool.reconnects`` on the next pull)."""
    try:
        if pool is not None:
            cli = pool.get(addr)
            return {
                "addr": addr,
                "health": cli.health(),
                "bundle": cli.debug(),
                "error": None,
            }
        with ServiceClient(addr, timeout_s=timeout_s) as cli:
            return {
                "addr": addr,
                "health": cli.health(),
                "bundle": cli.debug(),
                "error": None,
            }
    except Exception as e:  # noqa: BLE001 — a dead process is a gap row
        if pool is not None:
            pool.invalidate(addr)
        return {"addr": addr, "health": None, "bundle": None,
                "error": f"{type(e).__name__}: {e}"}


def collect(router_addr: str, timeout_s: float = 10.0,
            pool: ClientPool | None = None) -> dict:
    """One merged fleet bundle (pure data; writing is separate).

    The router's health reply advertises every shard replica address;
    each is pulled for its own inline bundle and tagged with its shard
    index. ``processes`` counts how many actually handed one over.
    Pass one :class:`ClientPool` across repeated collections to reuse
    every endpoint's connection."""
    router = _pull(router_addr, timeout_s, pool)
    replicas: list[dict[str, Any]] = []
    h = router["health"]
    if h is not None:
        for ent in h.get("shards", []):
            for addr in ent.get("addrs", []):
                rep = _pull(addr, timeout_s, pool)
                rep["shard"] = ent.get("shard")
                replicas.append(rep)
    processes = sum(
        1 for p in [router, *replicas] if p["bundle"] is not None
    )
    return {
        "bundle": FLEET_BUNDLE_VERSION,
        "ts": time.time(),
        "wall_time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "router": router,
        "replicas": replicas,
        "processes": processes,
    }


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="pull the flight-recorder bundle of a sieve router "
                    "and every shard replica into one merged fleet bundle"
    )
    p.add_argument("router_addr", help="router host:port")
    p.add_argument("--out", default=None,
                   help="output directory (default fleet-debug-<stamp>)")
    p.add_argument("--timeout", type=float, default=10.0,
                   help="per-endpoint RPC timeout")
    args = p.parse_args(argv)
    # one pipelined client per endpoint for the whole collection
    # (ISSUE 14): the router is pulled once for its bundle and again
    # implicitly via health; both ride the same connection
    with ClientPool(timeout_s=args.timeout) as pool:
        fleet = collect(args.router_addr, timeout_s=args.timeout,
                        pool=pool)
    out = args.out or f"fleet-debug-{time.strftime('%Y%m%d-%H%M%S')}"
    os.makedirs(out, exist_ok=True)
    path = os.path.join(out, FLEET_BUNDLE_FILE)
    with open(path, "w") as f:
        json.dump(fleet, f, indent=1)
    unreachable = [p_["addr"] for p_ in [fleet["router"], *fleet["replicas"]]
                   if p_["bundle"] is None]
    print(json.dumps({
        "event": "fleet_bundle",
        "path": path,
        "processes": fleet["processes"],
        "unreachable": unreachable,
    }), flush=True)
    return 1 if unreachable else 0


if __name__ == "__main__":
    sys.exit(main())
