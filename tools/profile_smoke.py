"""Continuous-profiler smoke: a 2-shard subprocess fleet under induced
load, pulled into ONE merged flamegraph-compatible collapsed capture
where >= 90% of samples carry a thread-role tag, then an injected hot
frame (``svc_stall`` burning a worker inside ``server._handle``) that
``fleet_profile --diff`` must report as the top positive self-time
delta (ISSUE 20 acceptance; tier-1 via tests/test_profile.py).

Phases:

1. seed — sieve n into ``src``; split the segment ledger into two shard
   ledgers at a segment boundary E.
2. fleet — 2 ``python -m sieve serve`` shard subprocesses fronted by
   one ``python -m sieve route`` subprocess, all with ``--prof-hz 97``
   (fast beats keep the smoke short; production default is 19).
3. capture A — mixed exact workload across both shards, then
   ``tools/fleet_profile.py`` merges router + both replicas: all 3
   processes present (exit 0), the collapsed file parses, and
   role_tagged_fraction >= 0.9.
4. capture B + diff — ``svc_stall`` directives burn shard 1's worker
   pool inside ``server._handle`` (time.sleep is C-level, so the
   sampled leaf is the handler frame itself — a deterministic injected
   hot frame); a second capture is pulled under the stall load and
   ``fleet_profile --diff A B`` must name ``server._handle`` top
   positive delta.
5. gap — a ``svc_prof_gap`` directive drops shard 0's next profile
   reply: fleet_profile exits 1 naming the missing process, the
   partial merge still lands, and the next pull heals (exit 0).

Exit status: 0 on full parity (final line ``PROFILE_SMOKE_OK``), 1 on
any violation (with a FAIL line).

Usage: python tools/profile_smoke.py [--n N] [--keep DIR]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ORACLE_HI = 400_000
PROF_HZ = "97"  # fast smoke beats; the always-on default is 19


def fail(msg: str) -> None:
    print(f"FAIL: {msg}", flush=True)
    sys.exit(1)


def expect(desc: str, got, want) -> None:
    if got != want:
        fail(f"{desc}: got {got!r}, want {want!r}")


class Proc:
    """One ``sieve serve``/``sieve route`` subprocess + line collector."""

    def __init__(self, args: list[str], env: dict):
        self.args = args
        self.proc = subprocess.Popen(
            args, env=env, cwd=REPO, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        )
        head = self.proc.stdout.readline()
        try:
            self.serving = json.loads(head)
        except ValueError:
            self.proc.kill()
            raise RuntimeError(f"process did not announce itself: {head!r}")
        self.addr = self.serving["addr"]
        self._reader = threading.Thread(target=self._drain, daemon=True)
        self._reader.start()

    def _drain(self) -> None:
        for _ in self.proc.stdout:
            pass

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=10)


def run_fleet_profile(args: list[str], env: dict) -> tuple[int, dict, str]:
    """Run tools/fleet_profile.py; returns (rc, summary event, stdout)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_profile.py"),
         *args],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    summary = {}
    for ln in proc.stdout.splitlines():
        if ln.startswith("{"):
            summary = json.loads(ln)
    return proc.returncode, summary, proc.stdout


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--n", type=int, default=120_000)
    p.add_argument("--keep", default=None,
                   help="use (and keep) this work dir instead of a temp dir")
    args = p.parse_args(argv)
    if args.n > ORACLE_HI // 2:
        fail(f"--n must stay at or below {ORACLE_HI // 2} (oracle headroom)")

    from sieve.checkpoint import Ledger
    from sieve.config import SieveConfig
    from sieve.coordinator import run_local
    from sieve.service import ServiceClient

    workdir = args.keep or tempfile.mkdtemp(prefix="profile_smoke.")
    src = os.path.join(workdir, "src")
    procs: list[Proc] = []
    try:
        # --- phase 1: sieve src, split segments into two shard ledgers ---
        src_cfg = SieveConfig(
            n=args.n, backend="cpu-numpy", packing="wheel30",
            n_segments=8, quiet=True, checkpoint_dir=src,
        )
        print(f"phase 1: sieving source dir (n={args.n}, 8 segments)",
              flush=True)
        run_local(src_cfg)
        segs = sorted(
            Ledger.open_readonly(src_cfg).completed().values(),
            key=lambda r: r.lo,
        )
        E = segs[4].lo  # the shard edge, on a segment boundary
        dirs = [os.path.join(workdir, d) for d in ("shard0", "shard1")]
        for d, part in zip(dirs, (segs[:4], segs[4:])):
            led = Ledger.open(dataclasses.replace(src_cfg, checkpoint_dir=d))
            for r in part:
                led.record(r)
        print(f"phase 1 OK: shard ledgers split at edge E={E}", flush=True)

        # --- phase 2: 1 replica per shard + router, sampler at 97 Hz ----
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)

        def serve_args(d: str, range_lo: int) -> list[str]:
            a = [
                sys.executable, "-m", "sieve", "serve",
                "--addr", "127.0.0.1:0", "--n", str(args.n),
                "--packing", "wheel30", "--segments", "8",
                "--checkpoint-dir", d, "--deadline-s", "10",
                "--drain-s", "10", "--quiet", "--allow-chaos",
                "--prof-hz", PROF_HZ,
            ]
            if range_lo > 2:
                a += ["--range-lo", str(range_lo)]
            return a

        s0 = Proc(serve_args(dirs[0], 2), env)
        s1 = Proc(serve_args(dirs[1], E), env)
        procs.extend([s0, s1])
        router = Proc([
            sys.executable, "-m", "sieve", "route",
            "--addr", "127.0.0.1:0", "--quiet", "--allow-chaos",
            "--deadline-s", "10", "--timeout-s", "15",
            "--prof-hz", PROF_HZ,
            "--shard", f"2:{E}={s0.addr}",
            "--shard", f"{E}:{args.n + 1}={s1.addr}",
        ], env)
        procs.append(router)
        expect("router announce event", router.serving["event"], "routing")
        cli = ServiceClient(router.addr, timeout_s=30)
        print(f"phase 2 OK: fleet up (router at {router.addr}, "
              f"sampler {PROF_HZ} Hz)", flush=True)

        # --- phase 3: induced load -> merged capture A ------------------
        def load(seconds: float) -> int:
            done = 0
            deadline = time.time() + seconds
            while time.time() < deadline:
                x = 5_000 + 9_000 * (done % 8)
                if not cli.query("pi", x=x).get("ok"):
                    fail(f"load pi({x}) failed")
                if not cli.query("count", lo=E + 10,
                                 hi=E + 2_000).get("ok"):
                    fail("load count failed")
                done += 1
            return done

        reqs = load(2.5)
        out_a = os.path.join(workdir, "cap_a")
        rc, summary, _ = run_fleet_profile(
            [router.addr, "--out", out_a], env)
        expect("capture A exit code", rc, 0)
        expect("capture A processes", summary.get("processes"), 3)
        expect("capture A unreachable", summary.get("unreachable"), [])
        frac = summary.get("role_tagged_fraction", 0.0)
        if frac < 0.9:
            fail(f"role-tagged fraction {frac} < 0.9 in capture A")
        collapsed = os.path.join(out_a, "fleet_profile.collapsed")
        with open(collapsed) as f:
            lines = [ln for ln in f.read().splitlines() if ln]
        if not lines:
            fail("capture A collapsed file is empty")
        for ln in lines:
            stack, _, count = ln.rpartition(" ")
            if not (stack and count.isdigit()):
                fail(f"malformed collapsed line: {ln!r}")
            if stack.split(";")[0] not in ("router", "shard0", "shard1"):
                fail(f"collapsed line missing process cell: {ln!r}")
        samples = summary.get("samples", 0)
        if samples < 50:
            fail(f"capture A holds only {samples} samples under load")
        print(f"phase 3 OK: {reqs} request rounds, merged capture A "
              f"({samples} samples, {len(lines)} stacks, "
              f"{frac:.0%} role-tagged)", flush=True)

        # --- phase 4: injected hot frame -> capture B + diff ------------
        # svc_stall burns a worker inside server._handle (time.sleep has
        # no Python frame of its own): the deterministic injected frame
        with ServiceClient(s1.addr, timeout_s=10) as c1:
            seq1 = c1.stats()["requests"]
            c1.inject_chaos(",".join(
                f"svc_stall:any@s{seq1 + j}:0.12" for j in range(1, 25)
            ))
        stall_done = threading.Event()

        def stall_load() -> None:
            with ServiceClient(router.addr, timeout_s=30) as c:
                for _ in range(24):
                    c.query("count", lo=E + 10, hi=E + 2_000)
            stall_done.set()

        t = threading.Thread(target=stall_load, daemon=True)
        t.start()
        time.sleep(1.2)  # sample mid-stall
        out_b = os.path.join(workdir, "cap_b")
        rc, summary, _ = run_fleet_profile(
            [router.addr, "--out", out_b], env)
        expect("capture B exit code", rc, 0)
        stall_done.wait(timeout=30)
        rc, diff_summary, diff_out = run_fleet_profile(
            ["--diff", os.path.join(out_a, "fleet_profile.json"),
             os.path.join(out_b, "fleet_profile.json"), "--top", "10"],
            env)
        expect("diff exit code", rc, 0)
        top = diff_summary.get("top_delta")
        if top != "server._handle":
            fail(f"injected hot frame not top positive delta: got {top!r} "
                 f"(diff table:\n{diff_out})")
        print("phase 4 OK: svc_stall burn surfaced as top positive "
              "delta server._handle", flush=True)

        # --- phase 5: svc_prof_gap -> partial merge, named, healed ------
        with ServiceClient(s0.addr, timeout_s=10) as c0:
            pulls0 = c0.stats()["profile_pulls"] \
                + c0.stats()["profile_gaps"]
            c0.inject_chaos(f"svc_prof_gap:any@s{pulls0 + 1}")
        out_c = os.path.join(workdir, "cap_c")
        rc, summary, _ = run_fleet_profile(
            [router.addr, "--out", out_c, "--timeout", "2"], env)
        expect("gapped capture exit code", rc, 1)
        expect("gapped capture names shard0",
               summary.get("unreachable"), ["shard0"])
        expect("gapped capture still merges the rest",
               summary.get("processes"), 2)
        if not os.path.exists(os.path.join(out_c,
                                           "fleet_profile.collapsed")):
            fail("partial merge wrote no collapsed file")
        out_d = os.path.join(workdir, "cap_d")
        rc, summary, _ = run_fleet_profile(
            [router.addr, "--out", out_d], env)
        expect("healed capture exit code", rc, 0)
        expect("healed capture processes", summary.get("processes"), 3)
        with ServiceClient(s0.addr, timeout_s=10) as c0:
            expect("shard0 counted the gap",
                   c0.stats()["profile_gaps"], 1)
        cli.close()
        print("phase 5 OK: gap dropped one reply (partial merge, exit 1, "
              "shard0 named), next pull healed", flush=True)
        print("PROFILE_SMOKE_OK", flush=True)
        return 0
    finally:
        for pr in procs:
            pr.kill()
        if args.keep is None:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
