"""Autotune the pallas kernel knobs on the machine at hand.

Sweeps SIEVE_PALLAS_ROWS (the fused-tile size), SIEVE_PALLAS_DMIN (the
C/D split point) and SIEVE_PALLAS_FLAT_MIN (the kernel-exit cutoff) by
coordinate descent and writes the winning values to ``tuned.json`` at the
repo root, which sieve/kernels/pallas_mark.py loads at import (resolution
per knob: explicit env var > tuned.json > built-in default). ROADMAP
flagged that the built-in defaults were chosen in interpret mode; run
this once on real hardware to replace them with measured ones.

Each trial runs in a FRESH interpreter (the knobs are read at module
import) via ``--measure`` self-invocation, timing the warm fused
mark+reduce on one segment; the parent rejects any knob setting whose
(count, pairs, first, last) result differs from the baseline's, so a
fast-but-wrong configuration can never be written to tuned.json.

Usage: python tools/autotune_kernel.py [span] [lo]

    span  window size in values (default 1e9 on TPU, 3e6 in interpret
          mode — interpret timings rank knobs only roughly)
    lo    window start (default 2; use 999000000000 for the depth regime)
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import time

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

KNOBS = {
    "SIEVE_PALLAS_ROWS": [64, 128, 256],
    "SIEVE_PALLAS_DMIN": [4096, 8192, 16384],
    # 0 = the crossings-proportional auto cutoff; explicit values bracket
    # it for depth-regime windows
    "SIEVE_PALLAS_FLAT_MIN": [0, 1 << 22, 1 << 24, 1 << 26],
}
DEFAULTS = {
    "SIEVE_PALLAS_ROWS": 128,
    "SIEVE_PALLAS_DMIN": 4096,
    "SIEVE_PALLAS_FLAT_MIN": 0,
}


def measure(span: int, lo: int) -> None:
    """Child mode: knobs arrive via env; print one JSON line and exit."""
    import jax

    from sieve.kernels.jax_mark import TWIN_ADJ
    from sieve.kernels.pallas_mark import mark_pallas_fused, prepare_pallas
    from sieve.seed import seed_primes

    hi = lo + span
    seeds = seed_primes(math.isqrt(hi - 1))
    ps = prepare_pallas("odds", lo, hi, seeds)
    interpret = jax.devices()[0].platform != "tpu"
    res = mark_pallas_fused(ps, TWIN_ADJ, interpret)  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = mark_pallas_fused(ps, TWIN_ADJ, interpret)
        best = min(best, time.perf_counter() - t0)
        assert out == res, "nondeterministic kernel result"
    print(json.dumps({"seconds": best, "result": list(res)}))


def run_trial(knobs: dict, span: int, lo: int) -> dict | None:
    env = dict(os.environ)
    env.update({k: str(v) for k, v in knobs.items()})
    # a pre-existing tuned.json must not leak into the trial being measured
    env["SIEVE_TUNED_JSON"] = os.devnull
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--measure",
         str(span), str(lo)],
        env=env, capture_output=True, text=True,
    )
    if proc.returncode != 0:
        print(f"    trial {knobs} FAILED:\n{proc.stderr.strip()[-500:]}",
              file=sys.stderr)
        return None
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--measure":
        measure(int(float(sys.argv[2])), int(float(sys.argv[3])))
        return 0

    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    span = (
        int(float(sys.argv[1])) if len(sys.argv) > 1
        else (10**9 if on_tpu else 3 * 10**6)
    )
    lo = int(float(sys.argv[2])) if len(sys.argv) > 2 else 2
    print(f"autotune: span={span:.0e} lo={lo} "
          f"({'TPU' if on_tpu else 'interpret mode — rankings are rough'})")

    best = dict(DEFAULTS)
    base = run_trial(best, span, lo)
    if base is None:
        print("baseline trial failed; nothing written", file=sys.stderr)
        return 1
    best_s = base["seconds"]
    oracle = base["result"]
    print(f"baseline {best}: {best_s * 1e3:.1f} ms  result={oracle}")

    for name, candidates in KNOBS.items():
        for val in candidates:
            if val == best[name]:
                continue
            trial = {**best, name: val}
            out = run_trial(trial, span, lo)
            if out is None:
                continue
            if out["result"] != oracle:
                print(f"  {name}={val}: REJECTED (result {out['result']} "
                      f"!= {oracle})")
                continue
            print(f"  {name}={val}: {out['seconds'] * 1e3:.1f} ms")
            if out["seconds"] < best_s:
                best_s = out["seconds"]
                best = trial
        print(f"--> {name} = {best[name]}")

    path = os.path.join(REPO_ROOT, "tuned.json")
    payload = {
        **{k: int(v) for k, v in best.items()},
        "_meta": {
            "span": span,
            "lo": lo,
            "platform": "tpu" if on_tpu else "interpret",
            "best_ms": round(best_s * 1e3, 2),
            "result": oracle,
        },
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    print(f"wrote {path}: {json.dumps({k: best[k] for k in sorted(best)})}")
    if not on_tpu:
        print("note: interpret-mode timings tune vector-op counts, not HBM "
              "behavior; re-run on hardware before trusting these numbers")
    return 0


if __name__ == "__main__":
    sys.exit(main())
