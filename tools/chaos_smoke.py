"""Chaos smoke: a composed fault schedule plus a corrupted-ledger resume,
asserting exact counts end to end (ISSUE 6 satellite; tier-1 via
tests/test_chaos.py).

Phase 1 runs the cpu-cluster backend under four composed faults — a
worker kill, a mid-segment disconnect, heartbeat suppression, and a
silent reply stall — with checkpointing on, and requires bit-exact
pi/twin counts against a single-process cpu-numpy run of the same n.
The stall is sized under the adaptive silence deadline's heartbeat-miss
floor, so a stalled-but-alive worker must NOT be declared failed.

Phase 2 truncates the ledger mid-file (simulating a torn write on a
filesystem without the fsync guarantees) and re-runs with --resume: the
damaged file must be quarantined, every complete entry salvaged, and the
resumed run must again produce exact counts.

Exit status: 0 on full parity, 1 on any mismatch (with a FAIL line).

Usage: python tools/chaos_smoke.py [--n N] [--keep DIR]
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHAOS = "kill:any@s2,disconnect:any@s3,drop_hb:any@s4,stall:any@s5:1.5"


def fail(msg: str) -> None:
    print(f"FAIL: {msg}", flush=True)
    sys.exit(1)


def _assert_lock_orders() -> None:
    """SIEVE_LOCK_DEBUG=1: the orders the run actually acquired must
    agree with the static canonical order (sieve/analysis/model.py) —
    the smoke is the dynamic half of the concurrency gate."""
    from sieve import env
    from sieve.analysis import lockdebug

    if not env.env_flag("SIEVE_LOCK_DEBUG"):
        return
    problems = lockdebug.check_static_consistency()
    if problems:
        fail("lock sanitizer: observed orders disagree with the static "
             "graph:\n  " + "\n  ".join(problems))
    print(f"lock debug OK: {len(lockdebug.observed_pairs())} observed "
          f"acquisition orders consistent with the static graph",
          flush=True)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--n", type=int, default=10**5)
    p.add_argument("--keep", default=None,
                   help="use (and keep) this checkpoint dir instead of a "
                        "temp dir")
    args = p.parse_args(argv)

    # tight static floor = fast dead-worker detection; the adaptive
    # heartbeat-miss floor (4 x HEARTBEAT_S) still rides out the 1.5 s
    # stall. Short backoff keeps the disconnect reconnect snappy.
    os.environ.setdefault("SIEVE_CLUSTER_DEADLINE_S", "2")
    os.environ.setdefault("SIEVE_WORKER_BACKOFF_S", "0.05")

    from sieve.checkpoint import LEDGER_NAME
    from sieve.cluster import run_cluster
    from sieve.config import SieveConfig
    from sieve.coordinator import run_local

    workdir = args.keep or tempfile.mkdtemp(prefix="chaos_smoke.")
    try:
        oracle = run_local(SieveConfig(
            n=args.n, backend="cpu-numpy", twins=True, quiet=True,
        ))
        cfg = SieveConfig(
            n=args.n, backend="cpu-cluster", workers=2, n_segments=8,
            twins=True, quiet=True, coordinator_addr="127.0.0.1:0",
            checkpoint_dir=workdir, chaos=CHAOS,
        )

        print(f"phase 1: composed chaos run ({CHAOS})", flush=True)
        res = run_cluster(cfg)
        if res.pi != oracle.pi:
            fail(f"chaos run pi={res.pi}, oracle pi={oracle.pi}")
        if res.twin_pairs != oracle.twin_pairs:
            fail(f"chaos run twins={res.twin_pairs}, "
                 f"oracle twins={oracle.twin_pairs}")
        if len({s.seg_id for s in res.segments}) != len(res.segments):
            fail("duplicate seg_id in merged results (ledger double-count)")
        print(f"phase 1 OK: pi={res.pi} twins={res.twin_pairs} "
              f"segments={len(res.segments)}", flush=True)

        ledger_path = os.path.join(workdir, LEDGER_NAME)
        text = open(ledger_path).read()
        with open(ledger_path, "w") as f:
            f.write(text[: int(len(text) * 0.6)])  # torn mid-file
        print("phase 2: ledger truncated to 60%, resuming", flush=True)

        res2 = run_cluster(SieveConfig(
            **{**cfg.to_dict(), "resume": True, "chaos": None}
        ))
        if not os.path.exists(ledger_path + ".quarantined"):
            fail("corrupt ledger was not quarantined")
        if res2.pi != oracle.pi or res2.twin_pairs != oracle.twin_pairs:
            fail(f"resumed run pi={res2.pi}/twins={res2.twin_pairs}, "
                 f"oracle {oracle.pi}/{oracle.twin_pairs}")
        print(f"phase 2 OK: pi={res2.pi} twins={res2.twin_pairs} "
              f"(salvage + resume exact)", flush=True)
        _assert_lock_orders()
        print("CHAOS_SMOKE_OK", flush=True)
        return 0
    finally:
        if args.keep is None:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
