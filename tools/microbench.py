"""Kernel microbenchmark (SURVEY.md M3 gate): steady-state mark_words
throughput on the default device for one big segment, separating compile
time from run time. Tune via SIEVE_TIER1_MAX / SIEVE_SPEC_BLOCK.

Usage: python tools/microbench.py [n] [n_segments]
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    use_pallas = "--pallas" in sys.argv
    n = int(float(args[0])) if args else 10**9
    n_segments = int(args[1]) if len(args) > 1 else 1

    import jax

    from sieve.backends.jax_backend import TWIN_KIND, prepare_segment
    from sieve.kernels import jax_mark
    from sieve.kernels.jax_mark import mark_words
    from sieve.seed import seed_primes
    from sieve.segments import plan_segments

    seeds = seed_primes(int(np.sqrt(n)))
    segs = plan_segments(n, n_segments)
    seg = segs[0]

    if use_pallas:
        from sieve.kernels.pallas_mark import mark_pallas, prepare_pallas

        ps = prepare_pallas("odds", seg.lo, seg.hi, seeds)
        print(
            f"PALLAS n={n:.0e} segs={n_segments} nbits={ps.nbits} "
            f"Wpad={ps.Wpad} SB={ps.B[0].shape[1]} SC={ps.C[0].shape[1]}"
        )

        def call():
            count, twins, first, last = mark_pallas(ps, TWIN_KIND["odds"], False)
            return [count, twins]

        ts = ps
    else:
        ts = prepare_segment("odds", seg.lo, seg.hi, seeds)
        print(
            f"n={n:.0e} segs={n_segments} nbits={ts.nbits} Wpad={ts.Wpad} "
            f"tier1={len(ts.periods)} patterns (TIER1_MAX={jax_mark.TIER1_MAX}) "
            f"tier2={ts.m2.size} specs (SPEC_BLOCK={jax_mark.SPEC_BLOCK})"
        )

        def call():
            out = mark_words(
                ts.Wpad, TWIN_KIND["odds"], ts.periods, np.int32(ts.nbits),
                ts.patterns, ts.m2, ts.r2, ts.K2, ts.rcp2, ts.act2,
                ts.corr_idx, ts.corr_mask, np.uint32(ts.pair_mask),
            )
            return list(np.asarray(out))  # packed uint32[4]

    t0 = time.perf_counter()
    out = call()
    compile_s = time.perf_counter() - t0
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = call()
        times.append(time.perf_counter() - t0)
    best = min(times)
    bits = ts.nbits
    print(
        f"compile={compile_s:.1f}s run(best of 3)={best * 1000:.1f}ms "
        f"({2 * bits / best:.3e} values/s for this segment) count={out[0]}"
    )
    return 0


if __name__ == "__main__":
    main()
