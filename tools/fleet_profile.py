"""Fleet-wide flame pull: merge every process's continuous-profiler
table into ONE flamegraph-compatible collapsed file (ISSUE 20).

Asks the router for its ``profile`` wire op (answered inline by the
per-connection reader, so a wedged worker pool still profiles), reads
the shard replica addresses out of the router's health reply, pulls
each replica's profile the same way, and merges them — each stack key
prefixed with its process label, so the flame keeps one cell per
process. Writes:

* ``fleet_profile.collapsed`` — ``stack count`` lines, hottest first
  (``flamegraph.pl`` / speedscope load this directly), and
* ``fleet_profile.json`` — the raw per-process documents plus the
  merged table, for ``--diff`` and the tests.

Also prints a top-N per-frame SELF-time table (samples where the frame
was the leaf — time in the frame itself, not its callees).

Exit 1 when the router is unreachable or any advertised replica failed
to hand over a profile (e.g. a ``svc_prof_gap`` chaos drop) — the
partial merge is still written, each missing process named, and the
next pull heals.

Diff two captures (anomaly-correlated flame diff)::

    python tools/fleet_profile.py --diff before.json after.json

compares per-frame self-time SHARES (captures of different lengths
stay comparable); the top positive delta is the frame that got hotter.

Usage:
    python tools/fleet_profile.py 127.0.0.1:7733 [--out DIR]
        [--timeout S] [--top N]
    python tools/fleet_profile.py --diff OLD.json NEW.json [--top N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sieve.profile import (  # noqa: E402
    collapse_lines,
    diff_shares,
    merge_stacks,
    role_tagged_fraction,
    self_times,
)
from sieve.service.client import ClientPool  # noqa: E402

FLEET_PROFILE_VERSION = "sieve-fleet-profile/1"
COLLAPSED_FILE = "fleet_profile.collapsed"
PROFILE_FILE = "fleet_profile.json"


def _pull(addr: str, pool: ClientPool) -> dict[str, Any]:
    """One endpoint's health + inline profile, or a named error."""
    try:
        cli = pool.get(addr)
        return {"addr": addr, "health": cli.health(),
                "profile": cli.profile(), "error": None}
    except Exception as e:  # noqa: BLE001 — a dropped reply is a gap row
        pool.invalidate(addr)
        return {"addr": addr, "health": None, "profile": None,
                "error": f"{type(e).__name__}: {e}"}


def collect(router_addr: str, pool: ClientPool) -> dict:
    """Pull router + every advertised replica; merge (pure data).

    Process labels — ``router`` and ``shard<k>[.r<i>]`` — become the
    first flame cell; a replica whose profiler is disabled (hz=0)
    contributes no stacks but is not an error."""
    router = _pull(router_addr, pool)
    router["label"] = "router"
    replicas: list[dict[str, Any]] = []
    h = router["health"]
    if h is not None:
        for ent in h.get("shards", []):
            addrs = ent.get("addrs", [])
            for i, addr in enumerate(addrs):
                rep = _pull(addr, pool)
                rep["shard"] = ent.get("shard")
                rep["label"] = (f"shard{ent.get('shard')}"
                                + (f".r{i}" if len(addrs) > 1 else ""))
                replicas.append(rep)
    merged = merge_stacks([
        (p["label"], p["profile"])
        for p in [router, *replicas] if p["profile"] is not None
    ])
    return {
        "profile": FLEET_PROFILE_VERSION,
        "ts": time.time(),
        "wall_time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "router": router,
        "replicas": replicas,
        "merged": {k: [v["count"], v["role"]] for k, v in merged.items()},
        "role_tagged_fraction": round(role_tagged_fraction(merged), 4),
    }


def load_merged(path: str) -> dict[str, dict]:
    """The merged stack table out of a saved ``fleet_profile.json``."""
    with open(path) as f:
        doc = json.load(f)
    return {k: {"count": v[0], "role": v[1]}
            for k, v in doc.get("merged", {}).items()}


def _print_self_times(merged: dict[str, dict], top: int) -> None:
    rows = self_times(merged, top)
    print(f"{'self':>6}  {'share':>6}  frame")
    for r in rows:
        print(f"{r['self']:>6}  {r['share']:>6.1%}  {r['frame']}")


def run_diff(old_path: str, new_path: str, top: int) -> int:
    old, new = load_merged(old_path), load_merged(new_path)
    rows = diff_shares(old, new, top)
    print(f"{'delta':>7}  {'before':>7}  {'after':>7}  frame")
    for r in rows:
        print(f"{r['delta']:>+7.1%}  {r['before']:>7.1%}  "
              f"{r['after']:>7.1%}  {r['frame']}")
    print(json.dumps({
        "event": "fleet_profile_diff",
        "old": old_path, "new": new_path,
        "top_delta": rows[0]["frame"] if rows else None,
        "frames": len(rows),
    }), flush=True)
    return 0


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="merge the continuous-profiler tables of a sieve "
                    "router and every shard replica into one "
                    "flamegraph-compatible collapsed capture"
    )
    p.add_argument("router_addr", nargs="?", default=None,
                   help="router host:port (omit with --diff)")
    p.add_argument("--out", default=None,
                   help="output directory (default fleet-profile-<stamp>)")
    p.add_argument("--timeout", type=float, default=10.0,
                   help="per-endpoint RPC timeout")
    p.add_argument("--top", type=int, default=15,
                   help="rows in the self-time / diff table")
    p.add_argument("--diff", nargs=2, metavar=("OLD", "NEW"),
                   help="diff two saved fleet_profile.json captures "
                        "(per-frame self-time share deltas)")
    args = p.parse_args(argv)

    if args.diff:
        return run_diff(args.diff[0], args.diff[1], args.top)
    if not args.router_addr:
        p.error("router_addr is required unless --diff is given")

    with ClientPool(timeout_s=args.timeout) as pool:
        fleet = collect(args.router_addr, pool)
    merged = {k: {"count": v[0], "role": v[1]}
              for k, v in fleet["merged"].items()}

    out = args.out or f"fleet-profile-{time.strftime('%Y%m%d-%H%M%S')}"
    os.makedirs(out, exist_ok=True)
    collapsed_path = os.path.join(out, COLLAPSED_FILE)
    with open(collapsed_path, "w") as f:
        f.write("\n".join(collapse_lines(merged)) + "\n")
    json_path = os.path.join(out, PROFILE_FILE)
    with open(json_path, "w") as f:
        json.dump(fleet, f, indent=1)

    _print_self_times(merged, args.top)
    unreachable = [p_["label"] for p_ in
                   [fleet["router"], *fleet["replicas"]]
                   if p_["error"] is not None]
    print(json.dumps({
        "event": "fleet_profile",
        "collapsed": collapsed_path,
        "json": json_path,
        "processes": 1 + len(fleet["replicas"]) - len(unreachable),
        "unreachable": unreachable,
        "samples": sum(r["count"] for r in merged.values()),
        "role_tagged_fraction": fleet["role_tagged_fraction"],
    }), flush=True)
    return 1 if unreachable else 0


if __name__ == "__main__":
    sys.exit(main())
