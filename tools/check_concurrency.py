#!/usr/bin/env python
"""Static concurrency gate for the service plane (ISSUE 15).

Runs the :mod:`sieve.analysis` pass over ``sieve/`` and fails on any
finding not waived in ``tools/concurrency_baseline.json``. The baseline
only ratchets *down*: new findings fail the gate immediately, stale
entries (baselined keys that no longer fire) print a warning so they
get pruned.

Usage::

    python tools/check_concurrency.py            # the gate
    python tools/check_concurrency.py --dump     # roles, edges, locks
    python tools/check_concurrency.py --rebaseline  # rewrite baseline

``--dump`` is how the canonical lock order in
``sieve/analysis/model.py`` was derived; re-run it when adding locks.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASELINE_PATH = os.path.join(REPO, "tools", "concurrency_baseline.json")


def run_analysis(root: str | None = None):
    from sieve.analysis import checks, core, model

    root = root or os.path.join(REPO, "sieve")
    prog = core.scan(root, pkg="sieve", return_types=model.RETURN_TYPES)
    m = model.default_model()
    return prog, m, checks.analyze(prog, m)


def load_baseline(path: str = BASELINE_PATH) -> set[str]:
    if not os.path.exists(path):
        return set()
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return set(data.get("waived", []))


def check() -> tuple[list[str], list[str]]:
    """(new_finding_lines, stale_baseline_keys) — gate fails on new."""
    _, _, findings = run_analysis()
    waived = load_baseline()
    live = {f.key for f in findings}
    new = [str(f) for f in findings if f.key not in waived]
    stale = sorted(waived - live)
    return new, stale


def _dump() -> None:
    from sieve.analysis import checks

    prog, m, findings = run_analysis()
    roles = checks.assign_roles(prog, m)
    print("== thread roles ==")
    by_role: dict[str, list[str]] = {}
    for q, rs in roles.items():
        for r in rs:
            by_role.setdefault(r, []).append(q)
    for r in sorted(by_role):
        print(f"  {r}: {len(by_role[r])} funcs")
    print("== locks ==")
    for lock in sorted(prog.lock_ids()):
        print(f"  {lock}")
    print("== acquisition edges ==")
    for (a, b), sites in sorted(checks.lock_edges(prog).items()):
        func, line = sites[0]
        print(f"  {a} -> {b}   ({func}:{line}, {len(sites)} sites)")
    print("== findings ==")
    for f in findings:
        print(f"  {f}")
    print(f"== {len(findings)} findings ==")


def _rebaseline() -> None:
    _, _, findings = run_analysis()
    data = {
        "comment": (
            "Waived pre-existing concurrency findings. Ratchet-only: "
            "check_concurrency.py fails on any key not listed here; "
            "remove entries as the findings get fixed."
        ),
        "waived": sorted({f.key for f in findings}),
    }
    with open(BASELINE_PATH, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"check_concurrency: baseline rewritten "
          f"({len(data['waived'])} waived) -> {BASELINE_PATH}")


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--dump" in argv:
        _dump()
        return 0
    if "--rebaseline" in argv:
        _rebaseline()
        return 0
    new, stale = check()
    for key in stale:
        print(f"check_concurrency: warning: stale baseline entry {key}",
              file=sys.stderr)
    if new:
        print("check_concurrency: FAIL — new findings (fix them or, for "
              "judged false positives, add to tools/concurrency_baseline"
              ".json):", file=sys.stderr)
        for line in new:
            print(f"  {line}", file=sys.stderr)
        return 1
    waived = len(load_baseline())
    print(f"check_concurrency: ok (0 new findings, {waived} waived"
          f"{', ' + str(len(stale)) + ' stale' if stale else ''})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
