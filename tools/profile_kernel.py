"""True device time of the pallas kernel: chain k calls with DISTINCT
inputs (defeats CSE), one final reduced fetch. Slope over k = kernel time.
Also times the postlude alone the same way.

Usage: python tools/profile_kernel.py [span] [lo]

    span  window size in values (default 1e9)
    lo    window start (default 2) — the 10^12-depth probe that exposed
          the group-D regime collapse (VERDICT.md round 5) is:

              python tools/profile_kernel.py 1e9 999000000000

          (full 78,498-seed set, ND=609 live group-D blocks)
"""

from __future__ import annotations

import math
import os
import sys
import time

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def slope(times_by_k):
    ks = sorted(times_by_k)
    k0, k1 = ks[0], ks[-1]
    return (times_by_k[k1] - times_by_k[k0]) / (k1 - k0)


def main():
    import jax
    import jax.numpy as jnp

    from sieve.kernels.pallas_mark import (
        _build_call,
        _postlude,
        prepare_pallas,
        spec_counts,
    )
    from sieve.seed import seed_primes

    span = int(float(sys.argv[1])) if len(sys.argv) > 1 else 10**9
    lo = int(float(sys.argv[2])) if len(sys.argv) > 2 else 2
    hi = lo + span
    seeds = seed_primes(math.isqrt(hi - 1))
    ps = prepare_pallas("odds", lo, hi, seeds)
    SB, SC = ps.B[0].shape[1], ps.C[0].shape[1]
    ND = ps.D[0].shape[0] if ps.D[3].any() else 0
    print(f"[{lo:.3e}, {hi:.3e}) Wpad={ps.Wpad} SB={SB} SC={SC} ND={ND} "
          f"tiers={spec_counts(ps)}")
    call = _build_call(ps.Wpad, SB, SC, ND, interpret=False)
    base = tuple(ps.A) + tuple(ps.B) + tuple(ps.C) + tuple(ps.D)

    def variants(k):
        """k distinct arg tuples: perturb one inert pad lane of Bact."""
        out = []
        for i in range(k):
            a = [x.copy() for x in base]
            a[11] = a[11].copy()  # Bact
            # flip an unused pad column's act (stays 0 -> harmless but
            # distinct constant folding identity)
            a[7] = a[7].copy()
            a[7][0, -1] = np.int32(1000003 + 2 * i)  # BrK pad lane, act=0
            out.append(tuple(a))
        return out

    def kernel_chain(k):
        vs = variants(k)

        @jax.jit
        def run():
            acc = jnp.uint32(0)
            for a in vs:
                w = call(*a)
                acc = acc + w[0, 0] + w[-1, -1]
            return acc

        return run

    times = {}
    for k in (1, 3):
        r = kernel_chain(k)
        int(r())
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            int(r())
            best = min(best, time.perf_counter() - t0)
        times[k] = best
        print(f"kernel chain k={k}: {best*1e3:8.1f} ms")
    kt = slope(times)
    print(f"--> kernel device time: {kt*1e3:8.1f} ms "
          f"({2 * ps.nbits / kt:.3e} values/s)")

    # postlude alone (includes the flat crossing-list scatter): run kernel
    # once, postlude k times on perturbed words
    def post_chain(k):
        a = base

        @jax.jit
        def run():
            w = call(*a)
            acc = jnp.uint32(0)
            for i in range(k):
                c, t, f, l = _postlude(
                    w ^ jnp.uint32(i), np.int32(ps.nbits),
                    np.uint32(ps.pair_mask), ps.corr_idx[0],
                    ps.corr_mask[0], 1, ps.flat_idx[0], ps.flat_mask[0])
                acc = acc + c.astype(jnp.uint32)
            return acc

        return run

    times = {}
    for k in (1, 3):
        r = post_chain(k)
        int(r())
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            int(r())
            best = min(best, time.perf_counter() - t0)
        times[k] = best
        print(f"postlude chain k={k}: {best*1e3:8.1f} ms")
    print(f"--> postlude device time: {slope(times)*1e3:8.1f} ms")


if __name__ == "__main__":
    main()
