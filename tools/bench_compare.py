"""Diff the two newest benchmark result files and gate on regressions.

The bench driver writes one ``BENCH_r<NN>.json`` (and one
``MULTICHIP_r<NN>.json``) per round into the repo root. Each BENCH file
carries the benchmark subprocess's ``rc``, its stderr ``tail`` (with
one JSON metric line per benchmark:
``{"metric": ..., "value": ..., "unit": "values/s/chip", ...}``), and
the last metric re-parsed under ``parsed``. This tool pairs the two
newest rounds by metric name and prints the delta for each; it exits
nonzero when any throughput metric (``unit == "values/s/chip"``,
``unit == "qps"`` for request throughput — ISSUE 14, or
``unit == "cold_throughput"`` for the mesh cold-drain values/s —
ISSUE 18) regressed by more than ``--threshold`` (default 10%), when
any latency
metric (``unit == "ms_p95"``) *increased* by more than the same
threshold (lower is better — the service p95 gate, ISSUE 9), when any
``unit == "overhead_ratio"`` metric exceeds the ABSOLUTE 1.05 ceiling
(the fleet-tracing <=5% budget, ISSUE 12 — applied even to a metric's
first round, since the ceiling needs no baseline), when any
``unit == "bytes_per_member"`` metric exceeds its absolute wire-cost
ceiling or grows past the threshold round-over-round (the binary frame
budget, ISSUE 16), when any ``unit == "scaling_ratio"`` metric falls
below the ABSOLUTE 0.7 floor (the multi-process qps-per-process gate,
ISSUE 17 — but only when the record's ``cpus`` covers its
``procs_max``: on a 1-core container extra processes time-slice one
core and the ratio measures the scheduler, not the architecture), or
when the newest round itself failed (``rc != 0`` / ``ok == false``).

Round order comes from the ``_r<NN>`` filename suffix, NOT mtime — a
re-checkout or ``touch`` must not reorder history.

Usage: python tools/bench_compare.py [--dir DIR] [--threshold 0.10]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

_ROUND_RE = re.compile(r"_r(\d+)\.json$")
# absolute overhead budgets (gates, not round-over-round diffs): every
# ``overhead_ratio`` metric must stay under its ceiling. Default 1.05
# (the fleet-tracing/recorder budget, ISSUE 12/13); the lock sanitizer
# gets 1.10 — it wraps every lock in the plane and is a debug mode,
# not an always-on tax (ISSUE 15).
_DEFAULT_OVERHEAD_CEILING = 1.05
_OVERHEAD_CEILINGS = {
    "service_lock_debug_overhead_ratio": 1.10,
}
# absolute wire-cost budgets (ISSUE 16): a ``bytes_per_member`` metric
# must stay under its ceiling regardless of history — the binary batch
# encoding measures ~27 B/member (17 sent + 9 received) vs ~70 for
# JSON, so 48 flags any drift back toward text-sized frames.
_DEFAULT_BYTES_CEILING = 48.0
_BYTES_CEILINGS: dict[str, float] = {}
# absolute scaling floor (ISSUE 17): a ``scaling_ratio`` metric (e.g.
# q4 / (4 * q1) for 4-process serving) must keep >= 0.7x of the
# single-process qps per added process — enforced only when the record
# says the host has at least ``procs_max`` CPUs; with fewer cores the
# processes time-slice and the ratio is reported but not gated.
_DEFAULT_SCALING_FLOOR = 0.7
_SCALING_FLOORS: dict[str, float] = {}


def find_rounds(bench_dir: str, prefix: str) -> list[tuple[int, str]]:
    """(round, path) pairs for ``<prefix>_r<NN>.json``, round ascending."""
    rounds = []
    for path in glob.glob(os.path.join(bench_dir, f"{prefix}_r*.json")):
        m = _ROUND_RE.search(os.path.basename(path))
        if m:
            rounds.append((int(m.group(1)), path))
    rounds.sort()
    return rounds


def extract_metrics(doc: dict) -> dict[str, dict]:
    """Metric-name -> record from a BENCH round document.

    Metrics live as JSON lines inside the stderr ``tail`` (one per
    benchmark); ``parsed`` duplicates the last one and covers old
    rounds whose tail was truncated past the metric lines.
    """
    metrics: dict[str, dict] = {}
    for line in doc.get("tail", "").splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec and "value" in rec:
            metrics[rec["metric"]] = rec
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and "metric" in parsed:
        metrics.setdefault(parsed["metric"], parsed)
    return metrics


def compare(
    old: dict[str, dict], new: dict[str, dict], threshold: float
) -> tuple[list[str], list[str]]:
    """(report lines, regression descriptions) for old -> new."""
    lines: list[str] = []
    regressions: list[str] = []
    new_names: list[str] = []
    for name in sorted(old.keys() | new.keys()):
        o, n = old.get(name), new.get(name)
        # absolute ceilings apply regardless of history — including a
        # metric's very first round, where there is no old value to diff
        ceiling = _OVERHEAD_CEILINGS.get(name, _DEFAULT_OVERHEAD_CEILING)
        if n is not None and n.get("unit") == "overhead_ratio" \
                and float(n["value"]) > ceiling:
            regressions.append(
                f"{name}: {float(n['value']):.4g} exceeds the absolute "
                f"{ceiling} overhead ceiling"
            )
            lines.append(
                f"  {name}: {float(n['value']):.4g} overhead_ratio  "
                f"REGRESSION (> {ceiling} absolute ceiling)"
            )
            continue
        bceiling = _BYTES_CEILINGS.get(name, _DEFAULT_BYTES_CEILING)
        if n is not None and n.get("unit") == "bytes_per_member" \
                and float(n["value"]) > bceiling:
            regressions.append(
                f"{name}: {float(n['value']):.4g} exceeds the absolute "
                f"{bceiling} bytes/member ceiling"
            )
            lines.append(
                f"  {name}: {float(n['value']):.4g} bytes_per_member  "
                f"REGRESSION (> {bceiling} absolute ceiling)"
            )
            continue
        if n is not None and n.get("unit") == "scaling_ratio":
            # absolute per-process scaling floor (ISSUE 17) — only
            # meaningful when the host actually has a core per process;
            # otherwise the extra processes time-slice one core and the
            # ratio measures the scheduler, so report without gating
            floor = _SCALING_FLOORS.get(name, _DEFAULT_SCALING_FLOOR)
            cpus = int(n.get("cpus") or 0)
            procs_max = int(n.get("procs_max") or 0)
            gated = procs_max > 0 and cpus >= procs_max
            if gated and float(n["value"]) < floor:
                regressions.append(
                    f"{name}: {float(n['value']):.4g} below the absolute "
                    f"{floor} per-process scaling floor "
                    f"(cpus={cpus} >= procs_max={procs_max})"
                )
                lines.append(
                    f"  {name}: {float(n['value']):.4g} scaling_ratio  "
                    f"REGRESSION (< {floor} absolute floor)"
                )
                continue
            if not gated:
                lines.append(
                    f"  {name}: {float(n['value']):.4g} scaling_ratio  "
                    f"(ungated: cpus={cpus} < procs_max={procs_max})"
                )
                continue
        if o is None:
            # a metric present only in the newest round is reported
            # explicitly (it becomes next round's baseline), never
            # silently ignored
            new_names.append(name)
            lines.append(
                f"  {name}: NEW metric (no previous round) "
                f"{n['value']:.4g} {n.get('unit', '')}"
            )
            continue
        if n is None:
            lines.append(f"  {name}: GONE (was {o['value']:.4g})")
            regressions.append(f"{name} disappeared from the newest round")
            continue
        ov, nv = float(o["value"]), float(n["value"])
        delta = (nv - ov) / ov if ov else 0.0
        unit = n.get("unit", "")
        verdict = ""
        if unit == "values/s/chip" and delta < -threshold:
            # throughput: higher is better, gate on drops
            verdict = f"  REGRESSION (> {threshold:.0%} drop)"
            regressions.append(
                f"{name}: {ov:.4g} -> {nv:.4g} ({delta:+.1%})"
            )
        elif unit == "qps" and delta < -threshold:
            # request throughput (ISSUE 14): higher is better, gate on
            # drops — the service_hot_qps line rides this rule
            verdict = f"  REGRESSION (> {threshold:.0%} throughput drop)"
            regressions.append(
                f"{name}: {ov:.4g} qps -> {nv:.4g} qps ({delta:+.1%})"
            )
        elif unit == "cold_throughput" and delta < -threshold:
            # mesh cold-drain throughput (ISSUE 18): values/s through one
            # SPMD drain slice — higher is better, gate on drops
            verdict = f"  REGRESSION (> {threshold:.0%} cold-drain drop)"
            regressions.append(
                f"{name}: {ov:.4g} -> {nv:.4g} values/s ({delta:+.1%})"
            )
        elif unit == "ms_p95" and delta > threshold:
            # latency: lower is better, gate on increases
            verdict = f"  REGRESSION (> {threshold:.0%} p95 increase)"
            regressions.append(
                f"{name}: p95 {ov:.4g} ms -> {nv:.4g} ms ({delta:+.1%})"
            )
        elif unit == "bytes_per_member" and delta > threshold:
            # wire cost (ISSUE 16): lower is better, gate on increases
            # (on top of the absolute ceiling above)
            verdict = f"  REGRESSION (> {threshold:.0%} wire-cost increase)"
            regressions.append(
                f"{name}: {ov:.4g} -> {nv:.4g} bytes/member ({delta:+.1%})"
            )
        lines.append(
            f"  {name}: {ov:.4g} -> {nv:.4g} {unit} "
            f"({delta:+.1%}){verdict}"
        )
    if new_names:
        lines.append(
            f"  {len(new_names)} new metric(s) this round "
            f"(baseline from next round): {', '.join(new_names)}"
        )
    return lines, regressions


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="diff the two newest BENCH_r*.json rounds by metric "
        "name; exit nonzero on a >threshold throughput regression"
    )
    p.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)")
    p.add_argument("--threshold", type=float, default=0.10,
                   help="relative drop in a values/s/chip metric that "
                        "fails the gate (default 0.10)")
    args = p.parse_args(argv)

    failures: list[str] = []

    rounds = find_rounds(args.dir, "BENCH")
    if len(rounds) < 2:
        print(f"bench_compare: {len(rounds)} BENCH round(s) in "
              f"{args.dir} — need 2 to compare; nothing to gate")
        return 0

    (old_r, old_path), (new_r, new_path) = rounds[-2], rounds[-1]
    with open(old_path) as f:
        old_doc = json.load(f)
    with open(new_path) as f:
        new_doc = json.load(f)
    print(f"bench_compare: round r{old_r:02d} -> r{new_r:02d}")
    if new_doc.get("rc", 0) != 0:
        failures.append(
            f"newest BENCH round r{new_r:02d} failed (rc={new_doc['rc']})"
        )
    lines, regressions = compare(
        extract_metrics(old_doc), extract_metrics(new_doc), args.threshold
    )
    print("\n".join(lines) if lines else "  (no metrics parsed)")
    failures.extend(regressions)

    mc = find_rounds(args.dir, "MULTICHIP")
    if len(mc) >= 2:
        with open(mc[-1][1]) as f:
            mc_new = json.load(f)
        status = ("skipped" if mc_new.get("skipped")
                  else "ok" if mc_new.get("ok") else "FAILED")
        print(f"multichip r{mc[-1][0]:02d}: {status} "
              f"(n_devices={mc_new.get('n_devices')})")
        if not mc_new.get("ok") and not mc_new.get("skipped"):
            failures.append(
                f"newest MULTICHIP round r{mc[-1][0]:02d} failed "
                f"(rc={mc_new.get('rc')})"
            )

    if failures:
        for f_ in failures:
            print(f"bench_compare: FAIL: {f_}", file=sys.stderr)
        return 1
    print("bench_compare: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
