"""Observatory smoke: a 2-shard fleet under a mixed exact workload with
one injected latency regression and one injected scrape gap — the
observer's ring must contain the regression, exactly ONE
``fleet_anomaly`` bundle trigger must fire (zero false alarms, even
across the gap window), and the exemplar files must hold 100% of the
stalled requests' span trees plus at most a 10% healthy baseline
(ISSUE 19 acceptance; tier-1 via tests/test_observe.py).

Phases:

1. seed — sieve n into ``src``; split the segment ledger into two shard
   ledgers at a segment boundary E.
2. fleet — 2 ``python -m sieve serve`` shard subprocesses (each with a
   ``--debug-dir`` so exemplar files land on disk) fronted by one
   ``python -m sieve route`` subprocess, also with a debug dir.
3. steady — 8 scrape cycles of an in-process :class:`FleetObserver`
   (manual ``scrape_once`` between exact mixed-workload batches, so the
   trend windows are deterministic); a ``svc_scrape_gap:any@s5``
   directive eats one scrape — the gap is counted, no sample is
   fabricated, and NO anomaly fires anywhere in the phase.
4. regression — ``svc_stall`` directives on shard 1's next 10 requests
   under a 0.12 s deadline: every reply is the typed
   ``deadline_exceeded`` (never wrong), the next scrape's err_rate
   spikes, and exactly one ``fleet_anomaly`` fires, writing the merged
   fleet debug bundle. Three more steady scrapes must not re-fire
   (edge-trigger + cooldown).
5. exemplars — shard 1's ``exemplars.jsonl`` holds ALL 10 stalled
   requests (reason ``error``, with span trees), healthy baseline
   retention is <= 10% of healthy requests, and the router's kept
   exemplar for a stalled route carries the downstream shard records
   pulled over the ``exemplars`` wire op.
6. cli — ``python -m sieve observe --scrapes 3`` runs the daemon
   entrypoint against the live fleet; ``tools/fleet_top.py --once
   --observe-dir`` renders sparkline trend columns from its ring.

Exit status: 0 on full parity (final line ``OBSERVE_SMOKE_OK``), 1 on
any violation (with a FAIL line).

Usage: python tools/observe_smoke.py [--n N] [--keep DIR]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np

ORACLE_HI = 400_000


def fail(msg: str) -> None:
    print(f"FAIL: {msg}", flush=True)
    sys.exit(1)


def expect(desc: str, got, want) -> None:
    if got != want:
        fail(f"{desc}: got {got!r}, want {want!r}")


class Proc:
    """One ``sieve serve``/``sieve route`` subprocess + line collector."""

    def __init__(self, args: list[str], env: dict):
        self.args = args
        self.proc = subprocess.Popen(
            args, env=env, cwd=REPO, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        )
        head = self.proc.stdout.readline()
        try:
            self.serving = json.loads(head)
        except ValueError:
            self.proc.kill()
            raise RuntimeError(f"process did not announce itself: {head!r}")
        self.addr = self.serving["addr"]
        self.lines: list[str] = []
        self._reader = threading.Thread(target=self._drain, daemon=True)
        self._reader.start()

    def _drain(self) -> None:
        for line in self.proc.stdout:
            self.lines.append(line)

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=10)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--n", type=int, default=120_000)
    p.add_argument("--keep", default=None,
                   help="use (and keep) this work dir instead of a temp dir")
    args = p.parse_args(argv)
    if args.n > ORACLE_HI // 2:
        fail(f"--n must stay at or below {ORACLE_HI // 2} (oracle headroom)")

    from sieve.chaos import ChaosSchedule, parse_chaos
    from sieve.checkpoint import Ledger
    from sieve.config import SieveConfig
    from sieve.coordinator import run_local
    from sieve.seed import seed_primes
    from sieve.service import ServiceClient
    from sieve.service.exemplar import load_exemplars
    from sieve.service.observe import (
        RING_FILE,
        FleetObserver,
        ObserverSettings,
        read_ring,
    )

    P = seed_primes(ORACLE_HI)

    def o_pi(x: int) -> int:
        return int(np.searchsorted(P, x, side="right"))

    def o_count(lo: int, hi: int) -> int:
        return int(np.searchsorted(P, hi, side="left")
                   - np.searchsorted(P, lo, side="left"))

    workdir = args.keep or tempfile.mkdtemp(prefix="observe_smoke.")
    src = os.path.join(workdir, "src")
    obsdir = os.path.join(workdir, "obs")
    dbg = [os.path.join(workdir, d)
           for d in ("dbg_router", "dbg_shard0", "dbg_shard1")]
    procs: list[Proc] = []
    try:
        # --- phase 1: sieve src, split segments into two shard ledgers ---
        src_cfg = SieveConfig(
            n=args.n, backend="cpu-numpy", packing="wheel30",
            n_segments=8, quiet=True, checkpoint_dir=src,
        )
        print(f"phase 1: sieving source dir (n={args.n}, 8 segments)",
              flush=True)
        run_local(src_cfg)
        segs = sorted(
            Ledger.open_readonly(src_cfg).completed().values(),
            key=lambda r: r.lo,
        )
        E = segs[4].lo  # the shard edge, on a segment boundary
        dirs = [os.path.join(workdir, d) for d in ("shard0", "shard1")]
        for d, part in zip(dirs, (segs[:4], segs[4:])):
            led = Ledger.open(dataclasses.replace(src_cfg, checkpoint_dir=d))
            for r in part:
                led.record(r)
        print(f"phase 1 OK: shard ledgers split at edge E={E}", flush=True)

        # --- phase 2: 1 replica per shard + router, all with debug dirs --
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)

        def serve_args(d: str, range_lo: int, dbg_dir: str) -> list[str]:
            a = [
                sys.executable, "-m", "sieve", "serve",
                "--addr", "127.0.0.1:0", "--n", str(args.n),
                "--packing", "wheel30", "--segments", "8",
                "--checkpoint-dir", d, "--deadline-s", "10",
                "--drain-s", "10", "--quiet", "--allow-chaos",
                "--debug-dir", dbg_dir,
            ]
            if range_lo > 2:
                a += ["--range-lo", str(range_lo)]
            return a

        s0 = Proc(serve_args(dirs[0], 2, dbg[1]), env)
        s1 = Proc(serve_args(dirs[1], E, dbg[2]), env)
        procs.extend([s0, s1])
        router = Proc([
            sys.executable, "-m", "sieve", "route",
            "--addr", "127.0.0.1:0", "--quiet",
            "--deadline-s", "10", "--timeout-s", "15",
            "--debug-dir", dbg[0],
            "--shard", f"2:{E}={s0.addr}",
            "--shard", f"{E}:{args.n + 1}={s1.addr}",
        ], env)
        procs.append(router)
        expect("router announce event", router.serving["event"], "routing")
        cli = ServiceClient(router.addr, timeout_s=30)
        print(f"phase 2 OK: fleet up (router at {router.addr})", flush=True)

        # --- phase 3: steady scrapes + one injected scrape gap -----------
        obs = FleetObserver(
            router.addr,
            ObserverSettings(
                scrape_s=0.05, warmup=4, min_delta=2.0, z_threshold=8.0,
                cooldown_s=60.0, observe_dir=obsdir, quiet=True,
            ),
            chaos=ChaosSchedule(parse_chaos("svc_scrape_gap:any@s5")),
        )

        def steady_batch(i: int) -> None:
            # mixed exact workload across both shards
            x = 5_000 + 9_000 * (i % 8)
            expect(f"steady pi({x})", cli.query("pi", x=x)["value"], o_pi(x))
            expect(f"steady count s0 {i}",
                   cli.query("count", lo=10_000, hi=30_000)["value"],
                   o_count(10_000, 30_000))
            expect(f"steady count s1 {i}",
                   cli.query("count", lo=E + 10, hi=E + 2_000)["value"],
                   o_count(E + 10, E + 2_000))

        for s in range(1, 9):
            steady_batch(s)
            obs.scrape_once()
            st = obs.stats()
            if st["anomalies"]:
                fail(f"false alarm at steady scrape {s}: {st!r}")
        st = obs.stats()
        expect("one counted scrape gap", st["gaps"], 1)
        ring = read_ring(os.path.join(obsdir, RING_FILE))
        expect("ring rows after steady phase", len(ring), 8)
        gap_rows = [t for snap in ring for t in snap["targets"]
                    if t.get("gap")]
        expect("exactly one gap row in the ring", len(gap_rows), 1)
        expect("gap row fabricates no signals",
               "signals" in gap_rows[0], False)
        expect("gap at the injected scrape", ring[4]["scrape"], 5)
        print("phase 3 OK: 8 steady scrapes, 1 counted gap, 0 alarms",
              flush=True)

        # --- phase 4: svc_stall regression -> exactly one fleet_anomaly --
        with ServiceClient(s1.addr, timeout_s=10) as c1:
            seq1 = c1.stats()["requests"]
            c1.inject_chaos(",".join(
                f"svc_stall:any@s{seq1 + j}:0.25" for j in range(1, 11)
            ))
        stalled = 0
        for _ in range(10):
            rep = cli.query("count", lo=E + 10, hi=E + 2_000,
                            deadline_s=0.12)
            if rep.get("ok"):
                fail(f"stalled request answered ok under 0.12s budget: "
                     f"{rep!r}")
            expect("stalled request error kind", rep["error"],
                   "deadline_exceeded")
            stalled += 1
        obs.scrape_once()
        st = obs.stats()
        expect("exactly one fleet_anomaly fired", st["anomalies"], 1)
        ring = read_ring(os.path.join(obsdir, RING_FILE))
        reg = ring[-1]
        if not reg["anomalies"]:
            fail(f"regression scrape carries no anomaly row: {reg!r}")
        evid = reg["anomalies"][0]
        for key in ("addr", "signal", "value", "mean", "dev", "z",
                    "scrape"):
            if key not in evid:
                fail(f"anomaly evidence row missing {key!r}: {evid!r}")
        hot = [t for t in reg["targets"] if t["addr"] == s1.addr]
        if not hot or hot[0]["signals"]["err_rate"] <= 0:
            fail(f"ring does not contain the regression: {reg!r}")
        bundles = [f for f in os.listdir(obsdir)
                   if f.startswith("anomaly_")]
        expect("one anomaly bundle written", len(bundles), 1)
        with open(os.path.join(obsdir, bundles[0])) as f:
            doc = json.load(f)
        if not any(pr.get("bundle") for pr in doc["processes"]):
            fail(f"anomaly bundle pulled no recorder state: {bundles[0]}")
        for s in range(3):  # edge-trigger: no re-fire on the way down
            steady_batch(s)
            obs.scrape_once()
        expect("no anomaly re-fire after regression",
               obs.stats()["anomalies"], 1)
        print(f"phase 4 OK: {stalled} typed deadline_exceeded, one "
              f"fleet_anomaly ({evid['signal']} z={evid['z']}), one "
              f"bundle, no re-fire", flush=True)

        # --- phase 5: exemplar files ------------------------------------
        with ServiceClient(s1.addr, timeout_s=10) as c1:
            st1 = c1.stats()
        # exemplar appends ride a writer thread in the server process —
        # give the tail a moment to land before reading the files
        deadline = time.time() + 5.0
        while True:
            shard_recs = load_exemplars(
                os.path.join(dbg[2], "exemplars.jsonl"))
            errors = [r for r in shard_recs
                      if r.get("outcome") == "deadline_exceeded"]
            if len(errors) >= stalled or time.time() > deadline:
                break
            time.sleep(0.05)
        if len(errors) < stalled:
            fail(f"shard exemplar file holds {len(errors)} of {stalled} "
                 f"stalled requests")
        for r in errors:
            expect("stalled exemplar reason", r["reason"], "error")
            if not r.get("ctx"):
                fail(f"stalled exemplar carries no trace ctx: {r!r}")
        if not any(r.get("spans") for r in errors):
            fail("no stalled exemplar carries a span tree")
        healthy_seen = st1["exemplars_seen"] - len(errors)
        healthy_kept = len([r for r in shard_recs
                            if r.get("outcome") == "ok"])
        if healthy_kept > max(1, healthy_seen // 10):
            fail(f"healthy retention too high: {healthy_kept} of "
                 f"{healthy_seen}")
        deadline = time.time() + 5.0
        while True:
            router_recs = load_exemplars(
                os.path.join(dbg[0], "exemplars.jsonl"))
            routed_err = [r for r in router_recs
                          if r.get("outcome") not in (None, "ok")]
            if routed_err and any(r.get("downstream") for r in routed_err):
                break
            if time.time() > deadline:
                break
            time.sleep(0.05)
        if not routed_err:
            fail("router kept no exemplar for the stalled route")
        if not any(r.get("downstream") for r in routed_err):
            fail("router exemplar pulled no downstream shard records")
        live = cli.exemplars()
        if not live:
            fail("exemplars wire op returned nothing from the router")
        print(f"phase 5 OK: shard kept {len(errors)}/{stalled} stalled "
              f"(healthy {healthy_kept}/{healthy_seen}), router kept "
              f"{len(routed_err)} with downstream pulls", flush=True)

        # --- phase 6: the CLI daemon + fleet_top sparklines -------------
        obs2 = os.path.join(workdir, "obs2")
        proc = subprocess.run(
            [sys.executable, "-m", "sieve", "observe",
             "--router", router.addr, "--observe-dir", obs2,
             "--scrapes", "3", "--scrape-s", "0.1", "--quiet"],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=60,
        )
        if proc.returncode != 0:
            fail(f"sieve observe rc={proc.returncode}: {proc.stderr[-800:]}")
        lines = [json.loads(ln) for ln in proc.stdout.splitlines()
                 if ln.startswith("{")]
        expect("observe announce", lines[0]["event"], "observing")
        expect("observe summary", lines[-1]["event"], "observed")
        expect("observe CLI scrapes", lines[-1]["scrapes"], 3)
        expect("observe CLI ring rows",
               len(read_ring(os.path.join(obs2, RING_FILE))), 3)
        top = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "fleet_top.py"),
             router.addr, "--once", "--observe-dir", obsdir],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=60,
        )
        if top.returncode != 0:
            fail(f"fleet_top rc={top.returncode}: {top.stderr[-800:]}")
        if "hot trend" not in top.stdout:
            fail("fleet_top --observe-dir shows no trend columns")
        if not any(ch in top.stdout for ch in "▁▂▃▄▅▆▇█"):
            fail("fleet_top trend columns carry no sparkline")
        cli.close()
        print("phase 6 OK: observe CLI ran 3 scrapes, fleet_top rendered "
              "ring sparklines", flush=True)
        print("OBSERVE_SMOKE_OK", flush=True)
        return 0
    finally:
        for pr in procs:
            pr.kill()
        if args.keep is None:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
