#!/usr/bin/env python3
"""Run every static gate, one line per check, one exit code.

The individual checkers stay runnable on their own (each prints its
own diagnostics to stderr); this runner exists so CI and humans have a
single command that cannot silently skip a gate. Adding a checker
means adding a ``(name, main)`` pair to ``CHECKS``.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for p in (REPO, os.path.join(REPO, "tools")):
    if p not in sys.path:
        sys.path.insert(0, p)

import check_concurrency  # noqa: E402
import check_env_vars  # noqa: E402
import check_event_schema  # noqa: E402
import check_wire_ops  # noqa: E402

#: (display name, argv-style main returning an exit code)
CHECKS = (
    ("wire_ops", check_wire_ops.main),
    ("event_schema", check_event_schema.main),
    ("concurrency", check_concurrency.main),
    ("env_vars", check_env_vars.main),
)


def main(argv: list[str] | None = None) -> int:
    failed = []
    for name, entry in CHECKS:
        try:
            rc = entry([])
        except Exception as exc:  # a crashed checker is a failed checker
            print(f"check_all: {name} crashed: {exc!r}", file=sys.stderr)
            rc = 2
        print(f"check_all: {name}: {'ok' if rc == 0 else f'FAILED ({rc})'}")
        if rc != 0:
            failed.append(name)
    if failed:
        print(f"check_all: FAILED ({', '.join(failed)})", file=sys.stderr)
        return 1
    print(f"check_all: ok ({len(CHECKS)} checks)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
