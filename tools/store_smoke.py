"""Store smoke: eviction is a demotion, restart is a warm start, N
processes serve one port (ISSUE 17 acceptance; tier-1 via
tests/test_store.py).

Builds a sieved checkpoint dir, then drives the tiered segment store
through the full life cycle the issue promises:

1. burst-materialize under load — an in-process server with a
   deliberately tiny ``BitsetLRU`` (2 slots for 4 chunks) answers an
   oracle-exact hot burst while a ``store_torn_write`` chaos directive
   garbles one demotion mid-append: every answer stays exact, the torn
   record is counted (``torn_writes``), and by the end of the burst
   every chunk has been *demoted* into tier 2 of the store — eviction
   discards nothing.
2. multi-process warm restart — the server is stopped and the same dir
   is served again by ``python -m sieve serve --procs 3``: three full
   processes SO_REUSEPORT-bound to ONE port, each with a cold LRU. The
   same burst, fired over many fresh connections so the kernel spreads
   it across all three, must come back oracle-exact with **zero**
   re-materializations and zero cold dispatches fleet-wide — every
   chunk is answered out of the shared mmap'd store.
3. reply identity — the same ``primes`` query over nine fresh
   connections (landing on different processes) must produce replies
   that are byte-identical after stripping the per-request timing
   field, proving the processes serve one consistent store generation.
4. per-process accounting — SIGTERM to the supervisor fans out a
   graceful drain; each child's ``drained`` JSON line is parsed and
   asserted on individually (materialized == 0, cold_dispatches == 0,
   store hits > 0 fleet-wide, writer election: exactly one writer).

With SIEVE_LOCK_DEBUG=1 the in-process phase additionally asserts the
observed lock acquisition orders against the static canonical order.

Exit status: 0 on full parity (STORE_SMOKE_OK), 1 on any violation.

Usage: python tools/store_smoke.py [--n N] [--keep DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

ORACLE_HI = 400_000


def fail(msg: str) -> None:
    print(f"FAIL: {msg}", flush=True)
    sys.exit(1)


def expect(desc: str, got, want) -> None:
    if got != want:
        fail(f"{desc}: got {got!r}, want {want!r}")


def _assert_lock_orders() -> None:
    """SIEVE_LOCK_DEBUG=1: observed orders must match the static graph."""
    from sieve import env
    from sieve.analysis import lockdebug

    if not env.env_flag("SIEVE_LOCK_DEBUG"):
        return
    problems = lockdebug.check_static_consistency()
    if problems:
        fail("lock sanitizer: observed orders disagree with the static "
             "graph:\n  " + "\n  ".join(problems))
    print(f"lock debug OK: {len(lockdebug.observed_pairs())} observed "
          f"acquisition orders consistent with the static graph",
          flush=True)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--n", type=int, default=200_000)
    p.add_argument("--keep", default=None,
                   help="use (and keep) this checkpoint dir instead of a "
                        "temp dir")
    args = p.parse_args(argv)
    if args.n > ORACLE_HI // 2:
        fail(f"--n must stay at or below {ORACLE_HI // 2} (oracle headroom)")

    from sieve.config import SieveConfig
    from sieve.coordinator import run_local
    from sieve.seed import seed_primes
    from sieve.service import ServiceClient, ServiceSettings, SieveService

    P = seed_primes(ORACLE_HI)

    def o_pi(x: int) -> int:
        return int(np.searchsorted(P, x, side="right"))

    def o_count(lo: int, hi: int) -> int:
        return int(np.searchsorted(P, hi, side="left")
                   - np.searchsorted(P, lo, side="left"))

    workdir = args.keep or tempfile.mkdtemp(prefix="store_smoke.")
    svc = None
    proc = None
    try:
        cfg = SieveConfig(
            n=args.n, backend="cpu-numpy", packing="odds",
            n_segments=4, quiet=True, checkpoint_dir=workdir,
        )
        print(f"phase 0: sieving checkpoint dir (n={args.n})", flush=True)
        run_local(cfg)

        # the burst targets: prefix counts and windows spread over all 4
        # segments (= all 4 index chunks), everything inside [0, n)
        seg = args.n // 4
        burst = []
        for s in range(4):
            lo = s * seg
            burst.append(("pi", {"x": lo + seg // 2},
                          o_pi(lo + seg // 2)))
            burst.append(("count", {"lo": lo + 100, "hi": lo + seg - 100},
                          o_count(lo + 100, lo + seg - 100)))

        # --- phase 1: burst under load, evictions demote, torn counted ---
        cfg1 = SieveConfig(
            n=args.n, backend="cpu-numpy", packing="odds",
            n_segments=4, quiet=True, checkpoint_dir=workdir,
            chaos="store_torn_write:any@s2",  # garble the 2nd demotion
        )
        settings1 = ServiceSettings(
            workers=4, queue_limit=64, refresh_s=0.0, lru_segments=2,
        )
        svc = SieveService(cfg1, settings1).start()
        replies: dict[int, tuple] = {}
        rep_lock = threading.Lock()

        def fire(i: int, op: str, params: dict, want) -> None:
            try:
                with ServiceClient(svc.addr, timeout_s=30) as c:
                    rep = c.query(op, **params)
            except BaseException as e:  # noqa: BLE001 — surfaced via fail
                rep = {"ok": False, "error": "transport", "detail": repr(e)}
            with rep_lock:
                replies[i] = (rep, want)

        threads = [threading.Thread(target=fire, args=(i, op, dict(ps), w))
                   for i, (op, ps, w) in enumerate(burst * 3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        if any(t.is_alive() for t in threads):
            fail("phase 1 burst query hung")
        for i, (rep, want) in sorted(replies.items()):
            if not rep.get("ok"):
                fail(f"phase 1 burst query {i}: {rep!r}")
            expect(f"phase 1 burst query {i}", rep["value"], want)

        # cycle the 2-slot LRU through all 4 chunks until every chunk
        # has been demoted into tier 2 (a torn demotion re-materializes
        # and re-demotes on a later eviction)
        deadline = time.monotonic() + 30
        with ServiceClient(svc.addr, timeout_s=30) as c1:
            while True:
                st = svc.store.stats()
                if st["entries"][2] >= 4:
                    break
                if time.monotonic() > deadline:
                    fail(f"phase 1: only {st['entries'][2]}/4 chunks "
                         f"demoted to tier 2 ({st})")
                for op, ps, want in burst:
                    expect(f"phase 1 cycle {op}{ps}",
                           c1.query(op, **ps).get("value"), want)
            s1 = c1.stats()
        st1 = svc.store.stats()
        if st1["demotions"] < 4:
            fail(f"phase 1: {st1['demotions']} demotions, want >= 4")
        if st1["torn_writes"] < 1:
            fail(f"phase 1: injected store_torn_write never fired ({st1})")
        if s1["internal_errors"] != 0:
            fail(f"phase 1: {s1['internal_errors']} internal errors")
        print(f"phase 1 OK: burst exact under load; "
              f"{st1['demotions']} demotions, tier2={st1['entries'][2]}, "
              f"torn_writes={st1['torn_writes']} (answers stayed exact)",
              flush=True)
        svc.stop()
        svc = None
        _assert_lock_orders()

        # --- phase 2: 3-process warm restart over the shared store -------
        env2 = dict(os.environ, JAX_PLATFORMS="cpu")
        env2.pop("SIEVE_LOCK_DEBUG", None)  # children: no debug overhead
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env2["PYTHONPATH"] = repo + os.pathsep + env2.get("PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, "-m", "sieve", "serve", "--n", str(args.n),
             "--segments", "4", "--checkpoint-dir", workdir,
             "--addr", "127.0.0.1:0", "--procs", "3", "--quiet"],
            env=env2, stdout=subprocess.PIPE, text=True, cwd=repo)
        assert proc.stdout is not None
        line = proc.stdout.readline()
        try:
            doc = json.loads(line)
        except ValueError:
            fail(f"phase 2: unparseable serving line {line!r}")
        expect("phase 2 serving event", doc.get("event"), "serving")
        expect("phase 2 supervisor procs", doc.get("procs"), 3)
        addr = doc["addr"]
        print(f"phase 2: 3-proc fleet serving {addr}", flush=True)

        # many fresh connections: the kernel spreads them over all 3
        # processes, so every process answers part of the burst
        procs_seen = set()
        for rnd in range(3):
            for op, ps, want in burst:
                with ServiceClient(addr, timeout_s=30) as c:
                    expect(f"phase 2 {op}{ps}",
                           c.query(op, **ps).get("value"), want)
                    procs_seen.add(c.health().get("proc"))
        print(f"phase 2 OK: burst exact over processes {sorted(procs_seen)}",
              flush=True)

        # --- phase 3: byte-identical replies across processes ------------
        probe = {"op": "primes", "lo": seg - 200, "hi": seg + 200}
        canon = set()
        probe_procs = set()
        for i in range(9):
            with ServiceClient(addr, timeout_s=30) as c:
                rep = c.query(probe["op"], lo=probe["lo"], hi=probe["hi"])
                probe_procs.add(c.health().get("proc"))
            if not rep.get("ok"):
                fail(f"phase 3 probe {i}: {rep!r}")
            for k in ("elapsed_ms", "t_recv", "t_sent"):
                rep.pop(k, None)     # per-request timing legitimately varies
            rep.pop("source", None)  # lru vs store provenance may differ
            canon.add(json.dumps(rep, sort_keys=True).encode())
        if len(canon) != 1:
            fail(f"phase 3: {len(canon)} distinct reply encodings across "
                 f"processes {sorted(probe_procs)}")
        print(f"phase 3 OK: byte-identical replies from processes "
              f"{sorted(probe_procs)}", flush=True)

        # --- phase 4: drain, per-process accounting ----------------------
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=60)
        drained = []
        for ln in out.splitlines():
            try:
                d = json.loads(ln)
            except ValueError:
                continue
            if d.get("event") == "drained":
                drained.append(d)
        sup = [d for d in drained if d.get("supervisor")]
        kids = sorted((d for d in drained if not d.get("supervisor")),
                      key=lambda d: d.get("proc", -1))
        if len(sup) != 1 or not sup[0].get("clean"):
            fail(f"phase 4: supervisor did not drain clean: {sup}")
        if len(kids) != 3:
            fail(f"phase 4: want 3 per-process drained lines, got {kids}")
        store_hits = 0
        writers = 0
        for d in kids:
            st = d["stats"]
            if st["materialized"] != 0:
                fail(f"phase 4: proc {d['proc']} re-materialized "
                     f"{st['materialized']} chunks after restart "
                     f"(store miss): {d}")
            if st["cold_dispatches"] != 0 or st["cold_computes"] != 0:
                fail(f"phase 4: proc {d['proc']} went cold after restart: "
                     f"{d}")
            store_hits += st["store_hits"]
            writers += 1 if (d.get("store") or {}).get("writer") else 0
        if store_hits < 4:
            fail(f"phase 4: only {store_hits} store hits fleet-wide, "
                 f"want >= 4 (the burst was not served from the store)")
        if writers != 1:
            fail(f"phase 4: {writers} store writers elected, want exactly "
                 f"1 (proc 0)")
        if proc.returncode != 0:
            fail(f"phase 4: supervisor exit code {proc.returncode}")
        proc = None
        print(f"phase 4 OK: 3/3 procs drained clean, 0 re-materializations,"
              f" 0 cold dispatches, {store_hits} store hits, 1 writer",
              flush=True)

        print("STORE_SMOKE_OK", flush=True)
        return 0
    finally:
        if svc is not None:
            svc.stop()
        if proc is not None and proc.poll() is None:
            proc.kill()
        if args.keep is None:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
