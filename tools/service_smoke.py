"""Service smoke: the query server under composed chaos, oracle-exact
or typed — never silent, never wrong (ISSUE 7 acceptance; tier-1 via
tests/test_service.py).

Builds a sieved checkpoint dir, starts a :class:`SieveService` on it,
and drives real TCP clients through seven phases:

1. correctness sweep — every op (pi / count / nth_prime / primes) hot,
   cold, and straddling the covered boundary, bit-exact against a
   cpu-numpy oracle; malformed requests get typed ``bad_request``.
2. hot repeat — the same interior query five times: the index-hit
   counter must rise while the cold-compute counter stays flat
   (answered from the index, nothing re-sieved).
3. coalescing — two overlapping cold queries staggered inside the
   simulated backend latency: the follower must coalesce onto the
   leader's flight and both replies must be exact.
4. composed chaos — an injected ``backend_down`` window plus
   ``svc_stall`` (beyond the deadline) plus ``svc_shed``, then 10
   concurrent mixed queries: every reply is either oracle-exact or a
   typed overloaded / deadline_exceeded / degraded error. Health stays
   observable and hot queries stay exact while the backend is down.
5. recovery — health returns to ok and a cold query is exact again.
6. batched burst + write-back (ISSUE 9) — a fresh ``--persist-cold``
   server on the same dir takes 20 concurrent cold queries: every reply
   oracle-exact, the dispatch counter stays at or below the distinct
   grid chunks touched (single-digit, not 20), and the results land in
   the ledger — a restarted server answers the same burst entirely from
   its index (zero cold computes).
7. priority lanes under flood (ISSUE 10) — a pristine copy of the
   checkpoint dir serves a 20-thread cold flood concurrent with a hot
   stream: hot p95 stays within 5x the unloaded hot p95 (with a small
   absolute floor below which 5x is scheduler jitter), every cold query
   terminates oracle-exact or with a typed reply, cold-lane sheds carry
   ``lane: "cold"``, and the per-lane stats/health fields are live.

Exit status: 0 on full parity, 1 on any violation (with a FAIL line).

Usage: python tools/service_smoke.py [--n N] [--keep DIR]
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

ORACLE_HI = 400_000
ALLOWED_CHAOS_ERRORS = {"overloaded", "deadline_exceeded", "degraded"}


def fail(msg: str) -> None:
    print(f"FAIL: {msg}", flush=True)
    sys.exit(1)


def expect(desc: str, got, want) -> None:
    if got != want:
        fail(f"{desc}: got {got!r}, want {want!r}")


def _assert_lock_orders() -> None:
    """SIEVE_LOCK_DEBUG=1: the orders the run actually acquired must
    agree with the static canonical order (sieve/analysis/model.py) —
    the smoke is the dynamic half of the concurrency gate."""
    from sieve import env
    from sieve.analysis import lockdebug

    if not env.env_flag("SIEVE_LOCK_DEBUG"):
        return
    problems = lockdebug.check_static_consistency()
    if problems:
        fail("lock sanitizer: observed orders disagree with the static "
             "graph:\n  " + "\n  ".join(problems))
    print(f"lock debug OK: {len(lockdebug.observed_pairs())} observed "
          f"acquisition orders consistent with the static graph",
          flush=True)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--n", type=int, default=200_000)
    p.add_argument("--keep", default=None,
                   help="use (and keep) this checkpoint dir instead of a "
                        "temp dir")
    args = p.parse_args(argv)
    if args.n > ORACLE_HI // 2:
        fail(f"--n must stay at or below {ORACLE_HI // 2} (oracle headroom)")

    from sieve.config import SieveConfig
    from sieve.coordinator import run_local
    from sieve.seed import seed_primes
    from sieve.service import ServiceClient, ServiceSettings, SieveService

    # cpu-numpy oracle: one flat prime table, every answer derived from it
    P = seed_primes(ORACLE_HI)

    def o_pi(x: int) -> int:
        return int(np.searchsorted(P, x, side="right"))

    def o_count(lo: int, hi: int) -> int:
        return int(np.searchsorted(P, hi, side="left")
                   - np.searchsorted(P, lo, side="left"))

    def o_pairs(lo: int, hi: int, gap: int) -> int:
        w = P[(P >= lo) & (P < hi)]
        if w.size < 2:
            return 0
        idx = np.searchsorted(w, w + gap)
        ok = idx < w.size
        return int(np.count_nonzero(w[idx[ok]] == w[ok] + gap))

    def o_primes(lo: int, hi: int) -> list[int]:
        return [int(v) for v in P[(P >= lo) & (P < hi)]]

    workdir = args.keep or tempfile.mkdtemp(prefix="service_smoke.")
    workdir7 = workdir.rstrip("/") + ".lanes"
    svc = None
    try:
        cfg = SieveConfig(
            n=args.n, backend="cpu-numpy", packing="wheel30",
            n_segments=4, quiet=True, checkpoint_dir=workdir,
        )
        print(f"phase 0: sieving checkpoint dir (n={args.n})", flush=True)
        run_local(cfg)
        # phase 6 persists cold results into workdir's ledger; phase 7
        # needs the pristine coverage, so snapshot the dir now
        shutil.rmtree(workdir7, ignore_errors=True)
        shutil.copytree(workdir, workdir7)

        # small cold chunks + a simulated 0.3 s backend latency make the
        # coalescing and shed scenarios deterministic at this scale
        settings = ServiceSettings(
            workers=4, queue_limit=32, default_deadline_s=10.0,
            cold_chunk=1 << 17, cold_delay_s=0.3,
            wire_chaos=True,  # phase 4 injects faults over the wire
        )
        svc = SieveService(cfg, settings).start()
        cli = ServiceClient(svc.addr, timeout_s=30)
        covered = svc.index.covered_hi
        total = svc.index.total_primes
        expect("indexed total_primes", total, o_pi(covered - 1))
        print(f"phase 0 OK: serving {svc.addr}, covered_hi={covered}, "
              f"total_primes={total}", flush=True)

        # --- phase 1: every op, hot / cold / straddling, oracle-exact ----
        expect("pi(0)", cli.pi(0), 0)
        expect("pi(2)", cli.pi(2), 1)
        expect("pi hot interior", cli.pi(100_000), o_pi(100_000))
        expect("pi hot boundary", cli.pi(covered - 1), o_pi(covered - 1))
        expect("pi cold", cli.pi(350_000), o_pi(350_000))
        expect("count hot", cli.count(0, args.n), o_count(0, args.n))
        expect("count fully cold", cli.count(250_000, 300_000),
               o_count(250_000, 300_000))
        expect("count lo==hi", cli.count(1000, 1000), 0)
        expect("count twins hot", cli.count(1000, 50_000, "twins"),
               o_pairs(1000, 50_000, 2))
        expect("count cousins hot", cli.count(1000, 50_000, "cousins"),
               o_pairs(1000, 50_000, 4))
        expect("count twins straddling",
               cli.count(190_000, 210_000, "twins"),
               o_pairs(190_000, 210_000, 2))
        expect("nth_prime(5)", cli.nth_prime(5), 11)
        expect("nth_prime in index", cli.nth_prime(1000), int(P[999]))
        expect("nth_prime beyond index", cli.nth_prime(total + 500),
               int(P[total + 499]))
        expect("primes straddling", cli.primes(199_990, 200_010),
               o_primes(199_990, 200_010))
        expect("primes tiny window", cli.primes(13, 14), [13])
        for desc, msg in [
            ("pi non-int", {"op": "pi", "x": "nope"}),
            ("count hi<lo", {"op": "count", "lo": 10, "hi": 5}),
            ("count bad kind", {"op": "count", "lo": 2, "hi": 10,
                                "kind": "sexy"}),
            ("nth_prime k=0", {"op": "nth_prime", "k": 0}),
            ("unknown op", {"op": "frobnicate"}),
        ]:
            r = cli.query(**msg)
            if r.get("ok") or r.get("error") != "bad_request":
                fail(f"{desc}: expected typed bad_request, got {r!r}")
        print("phase 1 OK: all ops oracle-exact, bad requests typed",
              flush=True)

        # --- phase 2: hot repeat answers from the index, no re-sieve -----
        s0 = cli.stats()
        want = o_pi(150_000)
        for _ in range(5):
            expect("hot repeat pi(150000)", cli.pi(150_000), want)
        s1 = cli.stats()
        hits = s1["index_hits"] - s0["index_hits"]
        if hits < 4:
            fail(f"hot repeats: index_hits rose by {hits}, want >= 4")
        if s1["cold_computes"] != s0["cold_computes"]:
            fail("hot repeats triggered cold computes "
                 f"({s0['cold_computes']} -> {s1['cold_computes']})")
        print(f"phase 2 OK: 5 hot repeats, +{hits} index hits, "
              f"0 cold computes", flush=True)

        # --- phase 3: overlapping cold queries coalesce ------------------
        s0 = cli.stats()
        want = o_pi(390_000)
        got: list[int] = []
        errs: list[BaseException] = []

        def q() -> None:
            try:
                with ServiceClient(svc.addr, timeout_s=30) as c:
                    got.append(c.pi(390_000))
            except BaseException as e:  # noqa: BLE001 — surfaced via fail
                errs.append(e)

        t1, t2 = threading.Thread(target=q), threading.Thread(target=q)
        t1.start()
        time.sleep(0.12)  # inside the leader's 0.3 s simulated latency
        t2.start()
        t1.join(25)
        t2.join(25)
        if t1.is_alive() or t2.is_alive():
            fail("coalescing query hung (silent hang)")
        if errs:
            fail(f"coalescing query errored: {errs[0]!r}")
        expect("coalesced values", got, [want, want])
        s1 = cli.stats()
        if s1["coalesced"] - s0["coalesced"] < 1:
            fail("overlapping cold queries did not coalesce")
        print(f"phase 3 OK: follower coalesced "
              f"(+{s1['coalesced'] - s0['coalesced']}), both exact",
              flush=True)

        # --- phase 4: composed chaos -------------------------------------
        # backend_down on the next query opens a 2.5 s degraded window;
        # that query needs a fresh cold chunk so it must come back as a
        # typed degraded reply while hot queries keep answering exactly.
        cli.inject_chaos(f"backend_down:any@s{svc._seq + 1}:2.5")
        r = cli.query("count", lo=395_000, hi=398_000)
        if r.get("ok") or r.get("error") != "degraded":
            fail(f"cold query during backend_down: want typed degraded, "
                 f"got {r!r}")
        expect("health while degraded", cli.health()["status"], "degraded")
        expect("hot pi while degraded", cli.pi(100_000), o_pi(100_000))

        batch = [
            ("pi hot a", {"op": "pi", "x": 120_000}, o_pi(120_000)),
            ("pi hot b", {"op": "pi", "x": 50_000}, o_pi(50_000)),
            ("pi cold", {"op": "pi", "x": 370_000}, o_pi(370_000)),
            ("count hot", {"op": "count", "lo": 10_000, "hi": 90_000},
             o_count(10_000, 90_000)),
            ("twins hot", {"op": "count", "lo": 2, "hi": 30_000,
                           "kind": "twins"}, o_pairs(2, 30_000, 2)),
            ("nth in-index", {"op": "nth_prime", "k": 2000}, int(P[1999])),
            ("nth beyond", {"op": "nth_prime", "k": total + 100},
             int(P[total + 99])),
            ("primes hot", {"op": "primes", "lo": 150_000, "hi": 150_500},
             o_primes(150_000, 150_500)),
            ("primes straddle", {"op": "primes", "lo": 199_900,
                                 "hi": 200_100},
             o_primes(199_900, 200_100)),
            ("count hot big", {"op": "count", "lo": 2, "hi": 200_000},
             o_count(2, 200_000)),
        ]
        # one stall beyond the 1 s per-request deadline, one forced shed,
        # landing on two of the 10 upcoming sequence numbers
        seq = svc._seq
        cli.inject_chaos(f"svc_stall:any@s{seq + 3}:1.5")
        cli.inject_chaos(f"svc_shed:any@s{seq + 6}")
        replies: dict[str, dict] = {}
        rep_lock = threading.Lock()

        def fire(desc: str, msg: dict) -> None:
            try:
                with ServiceClient(svc.addr, timeout_s=30) as c:
                    op = msg.pop("op")
                    rep = c.query(op, deadline_s=1.0, **msg)
            except BaseException as e:  # noqa: BLE001
                rep = {"ok": False, "error": "transport",
                       "detail": repr(e)}
            with rep_lock:
                replies[desc] = rep

        threads = [
            threading.Thread(target=fire, args=(d, dict(m)))
            for d, m, _ in batch
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        if any(t.is_alive() for t in threads):
            fail("chaos batch query hung (silent hang)")
        expect("health during chaos batch", cli.health()["ok"], True)

        n_ok = 0
        tally: dict[str, int] = {}
        for desc, _, want in batch:
            rep = replies[desc]
            if rep.get("ok"):
                n_ok += 1
                expect(f"chaos batch {desc}", rep["value"], want)
            else:
                err = rep.get("error")
                tally[err] = tally.get(err, 0) + 1
                if err not in ALLOWED_CHAOS_ERRORS:
                    fail(f"chaos batch {desc}: untyped/unexpected error "
                         f"{rep!r}")
                if err == "deadline_exceeded" and not isinstance(
                        rep.get("partial"), dict):
                    fail(f"chaos batch {desc}: deadline_exceeded without "
                         f"a partial prefix: {rep!r}")
        if n_ok < 1:
            fail("chaos batch: no query survived — server not serving")
        if tally.get("overloaded", 0) < 1:
            fail(f"chaos batch: injected svc_shed produced no typed "
                 f"overloaded reply (errors: {tally})")
        if tally.get("deadline_exceeded", 0) < 1:
            fail(f"chaos batch: injected svc_stall produced no typed "
                 f"deadline_exceeded reply (errors: {tally})")
        print(f"phase 4 OK: {n_ok}/{len(batch)} exact, "
              f"typed errors {tally}", flush=True)

        # --- phase 5: recovery -------------------------------------------
        deadline = time.monotonic() + 10
        while cli.health()["status"] != "ok":
            if time.monotonic() > deadline:
                fail("health never recovered after backend_down window")
            time.sleep(0.1)
        expect("cold count after recovery", cli.count(395_000, 398_000),
               o_count(395_000, 398_000))
        s = cli.stats()
        if s["internal_errors"] != 0:
            fail(f"{s['internal_errors']} internal errors during smoke")
        for key in ("index_hits", "coalesced", "shed", "deadline_exceeded",
                    "degraded_replies"):
            if s[key] < 1:
                fail(f"stats[{key!r}] == 0 after smoke; scenario not "
                     f"exercised")
        print(f"phase 5 OK: recovered, cold exact again "
              f"(index_hits={s['index_hits']} "
              f"cold_computes={s['cold_computes']} "
              f"coalesced={s['coalesced']} shed={s['shed']})", flush=True)
        cli.close()
        svc.stop()

        # --- phase 6: batched burst + ledger write-back (ISSUE 9) --------
        # A fresh server with --persist-cold semantics on the SAME dir:
        # its cold cache is empty, so a 20-thread burst over uncovered
        # ranges must be answered by the batcher in a handful of backend
        # dispatches, and the results must be durable in the ledger.
        settings6 = ServiceSettings(
            workers=8, queue_limit=32, default_deadline_s=15.0,
            cold_chunk=1 << 17, cold_delay_s=0.2, refresh_s=0.2,
            persist_cold=True,
        )
        svc = SieveService(cfg, settings6).start()
        burst = (
            [("pi", {"x": 390_000}, o_pi(390_000))] * 10
            + [("pi", {"x": 300_000}, o_pi(300_000))] * 5
            + [("count", {"lo": 250_000, "hi": 350_000},
                o_count(250_000, 350_000))] * 5
        )
        # distinct grid chunks the burst can touch: targets {250000,
        # 300001, 350000, 390001} past covered_hi split at the single
        # 1<<17 grid boundary in range -> 5 distinct (lo, hi) keys
        max_chunks = 5

        def fire6(i: int, op: str, params: dict, want, out: dict,
                  lock: threading.Lock) -> None:
            try:
                with ServiceClient(svc.addr, timeout_s=30) as c:
                    rep = c.query(op, **params)
            except BaseException as e:  # noqa: BLE001
                rep = {"ok": False, "error": "transport", "detail": repr(e)}
            with lock:
                out[i] = (rep, want)

        out6: dict[int, tuple] = {}
        lock6 = threading.Lock()
        threads = [
            threading.Thread(target=fire6, args=(i, op, dict(ps), want,
                                                 out6, lock6))
            for i, (op, ps, want) in enumerate(burst)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(40)
        if any(t.is_alive() for t in threads):
            fail("batched burst query hung (silent hang)")
        for i, (rep, want) in sorted(out6.items()):
            if not rep.get("ok"):
                fail(f"batched burst query {i}: {rep!r}")
            expect(f"batched burst query {i}", rep["value"], want)
        with ServiceClient(svc.addr, timeout_s=10) as c6:
            s6 = c6.stats()
        if not (1 <= s6["cold_dispatches"] <= max_chunks):
            fail(f"burst of {len(burst)} cold queries took "
                 f"{s6['cold_dispatches']} backend dispatches, want 1.."
                 f"{max_chunks} (batching not happening)")
        if s6["cold_batched_chunks"] > max_chunks:
            fail(f"burst dispatched {s6['cold_batched_chunks']} chunks, "
                 f"want <= {max_chunks} (single-flight dedup broken)")
        if s6["cold_persisted"] < 1:
            fail("persist_cold server wrote nothing back to the ledger")
        print(f"phase 6a OK: 20-query cold burst -> "
              f"{s6['cold_dispatches']} dispatches over "
              f"{s6['cold_batched_chunks']} chunks, "
              f"{s6['cold_persisted']} persisted", flush=True)

        # restart: a brand-new server on the same dir must answer the
        # whole burst from its (now extended) index — zero cold computes
        svc.stop()
        svc = SieveService(cfg, ServiceSettings(
            workers=4, queue_limit=32, default_deadline_s=15.0,
            cold_chunk=1 << 17, cold_delay_s=0.2,
        )).start()
        with ServiceClient(svc.addr, timeout_s=30) as c6:
            for op, ps, want in burst:
                expect(f"post-restart {op}{ps}",
                       c6.query(op, **ps).get("value"), want)
            s6 = c6.stats()
        if s6["cold_computes"] != 0 or s6["cold_dispatches"] != 0:
            fail(f"restarted server re-sieved persisted ranges "
                 f"(cold_computes={s6['cold_computes']}, "
                 f"cold_dispatches={s6['cold_dispatches']})")
        print(f"phase 6b OK: restart answered the burst from the "
              f"persisted index (covered_hi={svc.index.covered_hi}, "
              f"0 cold computes)", flush=True)
        svc.stop()

        # --- phase 7: priority lanes under a cold flood (ISSUE 10) -------
        # A server on the pristine dir (covered_hi = n+1): 20 flood
        # threads issue distinct cold queries (each needs a backend
        # dispatch behind the 0.25 s saturation delay) while a hot
        # stream runs concurrently. The dedicated hot worker + the
        # bounded cold lane must keep hot p95 within 5x its unloaded
        # value, and every cold reply must be exact or typed.
        cfg7 = SieveConfig(
            n=args.n, backend="cpu-numpy", packing="wheel30",
            n_segments=4, quiet=True, checkpoint_dir=workdir7,
        )
        settings7 = ServiceSettings(
            workers=4, hot_workers=1, queue_limit=64, cold_queue_limit=8,
            default_deadline_s=20.0, cold_chunk=1 << 17, cold_delay_s=0.25,
            cold_age_s=0.5, refresh_s=0.0,
        )
        svc = SieveService(cfg7, settings7).start()

        def pctile(vals: list[float], q: float) -> float:
            vs = sorted(vals)
            return vs[max(0, int(len(vs) * q + 0.999999) - 1)]

        hot_x = [10_000 + 3_500 * i for i in range(40)]  # all < n: hot
        with ServiceClient(svc.addr, timeout_s=30) as c7:
            unloaded: list[float] = []
            for x in hot_x:
                t0 = time.monotonic()
                expect(f"phase 7 unloaded pi({x})", c7.pi(x), o_pi(x))
                unloaded.append(time.monotonic() - t0)
            p95_unloaded = pctile(unloaded, 0.95)

            cold_replies: dict[int, dict] = {}
            cl_lock = threading.Lock()

            def flood(i: int) -> None:
                # distinct targets -> distinct clipped grid chunks, so
                # the flood keeps the cold plane genuinely busy
                x = 210_000 + 8_900 * i
                try:
                    with ServiceClient(svc.addr, timeout_s=60) as c:
                        rep = c.query("pi", x=x)
                except BaseException as e:  # noqa: BLE001
                    rep = {"ok": False, "error": "transport",
                           "detail": repr(e)}
                with cl_lock:
                    cold_replies[i] = (x, rep)

            flood_threads = [threading.Thread(target=flood, args=(i,))
                             for i in range(20)]
            for t in flood_threads:
                t.start()
            loaded: list[float] = []
            for _ in range(3):  # hot stream concurrent with the flood
                for x in hot_x:
                    t0 = time.monotonic()
                    expect(f"phase 7 hot-under-flood pi({x})",
                           c7.pi(x), o_pi(x))
                    loaded.append(time.monotonic() - t0)
            for t in flood_threads:
                t.join(90)
            if any(t.is_alive() for t in flood_threads):
                fail("phase 7: cold flood query hung (silent parking)")
            p95_loaded = pctile(loaded, 0.95)
            # the 5x acceptance bound, with an absolute floor: below
            # ~25 ms, 5x an unloaded sub-ms p95 is scheduler jitter
            bound = max(5 * p95_unloaded, 0.025)
            if p95_loaded > bound:
                fail(f"phase 7: hot p95 under flood {p95_loaded * 1e3:.2f}"
                     f" ms exceeds bound {bound * 1e3:.2f} ms "
                     f"(unloaded p95 {p95_unloaded * 1e3:.2f} ms)")
            tally7: dict[str, int] = {}
            for i, (x, rep) in sorted(cold_replies.items()):
                if rep.get("ok"):
                    tally7["ok"] = tally7.get("ok", 0) + 1
                    expect(f"phase 7 cold pi({x})", rep["value"], o_pi(x))
                    continue
                err = rep.get("error")
                tally7[err] = tally7.get(err, 0) + 1
                if err not in ALLOWED_CHAOS_ERRORS:
                    fail(f"phase 7 cold pi({x}): untyped/unexpected "
                         f"error {rep!r}")
                if err == "overloaded" and rep.get("lane") != "cold":
                    fail(f"phase 7: cold-lane shed without lane detail: "
                         f"{rep!r}")
            if tally7.get("ok", 0) < 1:
                fail(f"phase 7: no cold query survived the flood "
                     f"({tally7})")
            s7 = c7.stats()
            h7 = c7.health()
        for key in ("queue_depth_hot", "queue_depth_cold", "brownout"):
            if key not in s7 or key not in h7:
                fail(f"phase 7: per-lane field {key!r} missing from "
                     f"stats/health")
        if s7["hot_admitted"] < len(hot_x) * 4:
            fail(f"phase 7: hot stream misclassified "
                 f"(hot_admitted={s7['hot_admitted']})")
        if s7["cold_admitted"] < 1:
            fail("phase 7: no cold query admitted on the cold lane")
        print(f"phase 7 OK: hot p95 {p95_unloaded * 1e3:.2f} ms unloaded"
              f" -> {p95_loaded * 1e3:.2f} ms under 20-thread cold flood"
              f" (bound {bound * 1e3:.2f} ms); cold outcomes {tally7}; "
              f"lane_shed_cold={s7['lane_shed_cold']} "
              f"demoted={s7['demoted']}", flush=True)
        _assert_lock_orders()
        print("SERVICE_SMOKE_OK", flush=True)
        return 0
    finally:
        if svc is not None:
            svc.stop()
        shutil.rmtree(workdir7, ignore_errors=True)
        if args.keep is None:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
