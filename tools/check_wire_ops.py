"""Static check: the server and router wire surfaces cannot drift
(ISSUE 14 satellite).

The router fronts the exact wire protocol the single server speaks —
that is its core contract (ISSUE 11) — but nothing used to enforce it:
a wire op added to ``sieve/service/server.py`` and forgotten in
``sieve/service/router.py`` would silently bounce with
``bad_request: unknown op`` only at runtime, behind a fleet. This tool
regex-harvests every literal op (``op == "..."``) and message type
(``mtype == "..."``) each dispatcher handles and asserts:

* every server query op is routed (and vice versa — the router must
  not invent ops the server cannot answer);
* every server message type is either routed or explicitly listed in
  ``router.UNROUTED_TYPES`` (typed-rejected, with the reason written
  next to the constant);
* the ``batch`` op (ISSUE 14) appears on BOTH sides.

Importable (``from tools.check_wire_ops import check``) so the tier-1
suite runs it; ``main`` prints the verdict for CI / hook use.
"""

from __future__ import annotations

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sieve.service import router as _router_mod  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SERVER_PY = os.path.join(_REPO, "sieve", "service", "server.py")
ROUTER_PY = os.path.join(_REPO, "sieve", "service", "router.py")

# literal comparisons in the dispatchers; != catches the
# `if mtype != "query"` fall-through style
_OP_RE = re.compile(r'\bop\s*(?:==|!=)\s*"(\w+)"')
_MTYPE_RE = re.compile(r'\bmtype\s*(?:==|!=)\s*"(\w+)"')


def harvest(path: str) -> tuple[set[str], set[str]]:
    """(query ops, message types) a dispatcher source handles."""
    with open(path) as f:
        src = f.read()
    return set(_OP_RE.findall(src)), set(_MTYPE_RE.findall(src))


def check() -> list[str]:
    """Every wire-surface drift found; empty list means parity holds."""
    server_ops, server_types = harvest(SERVER_PY)
    router_ops, router_types = harvest(ROUTER_PY)
    unrouted = set(getattr(_router_mod, "UNROUTED_TYPES", ()))
    problems: list[str] = []
    for op in sorted(server_ops - router_ops):
        problems.append(
            f"server op {op!r} is not handled by the router "
            "(add it to SieveRouter._execute or reject it explicitly)"
        )
    for op in sorted(router_ops - server_ops):
        problems.append(
            f"router op {op!r} has no server-side handler "
            "(SieveService._execute does not know it)"
        )
    for t in sorted(server_types - router_types - unrouted):
        problems.append(
            f"server message type {t!r} is neither routed nor listed "
            "in router.UNROUTED_TYPES"
        )
    for t in sorted(unrouted & router_types):
        problems.append(
            f"message type {t!r} is in router.UNROUTED_TYPES but the "
            "router handles it — stale entry"
        )
    for side, ops in (("server", server_ops), ("router", router_ops)):
        if "batch" not in ops:
            problems.append(
                f"the batch op (ISSUE 14) is missing from the {side}"
            )
    return problems


def main(argv: list[str] | None = None) -> int:
    problems = check()
    for p in problems:
        print(f"check_wire_ops: {p}", file=sys.stderr)
    if problems:
        print(f"check_wire_ops: FAILED ({len(problems)} drift(s))",
              file=sys.stderr)
        return 1
    server_ops, server_types = harvest(SERVER_PY)
    print(
        f"check_wire_ops: ok ({len(server_ops)} ops, "
        f"{len(server_types)} message types in parity)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
