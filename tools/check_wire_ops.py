"""Static check: the server and router wire surfaces cannot drift
(ISSUE 14 satellite).

The router fronts the exact wire protocol the single server speaks —
that is its core contract (ISSUE 11) — but nothing used to enforce it:
a wire op added to ``sieve/service/server.py`` and forgotten in
``sieve/service/router.py`` would silently bounce with
``bad_request: unknown op`` only at runtime, behind a fleet. This tool
regex-harvests every literal op (``op == "..."``) and message type
(``mtype == "..."``) each dispatcher handles and asserts:

* every server query op is routed (and vice versa — the router must
  not invent ops the server cannot answer);
* every server message type is either routed or explicitly listed in
  ``router.UNROUTED_TYPES`` (typed-rejected, with the reason written
  next to the constant);
* the ``batch`` op (ISSUE 14) and the ``profile`` message type
  (ISSUE 20) appear on BOTH sides.

Binary wire v2 (ISSUE 16) adds a LIVE leg: ``check_encodings`` boots a
tiny in-process service and replays every query op through a v1 (JSON)
and a v2 (binary columns) client, asserting the decoded results are
byte-for-byte identical under canonical JSON — the codec cannot change
an answer, only its framing. ``check`` itself stays static (the tier-1
suite imports it); ``main`` runs both legs for CI / hook use.
"""

from __future__ import annotations

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sieve.service import router as _router_mod  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SERVER_PY = os.path.join(_REPO, "sieve", "service", "server.py")
ROUTER_PY = os.path.join(_REPO, "sieve", "service", "router.py")

# literal comparisons in the dispatchers; != catches the
# `if mtype != "query"` fall-through style
_OP_RE = re.compile(r'\bop\s*(?:==|!=)\s*"(\w+)"')
_MTYPE_RE = re.compile(r'\bmtype\s*(?:==|!=)\s*"(\w+)"')


def harvest(path: str) -> tuple[set[str], set[str]]:
    """(query ops, message types) a dispatcher source handles."""
    with open(path) as f:
        src = f.read()
    return set(_OP_RE.findall(src)), set(_MTYPE_RE.findall(src))


def check() -> list[str]:
    """Every wire-surface drift found; empty list means parity holds."""
    server_ops, server_types = harvest(SERVER_PY)
    router_ops, router_types = harvest(ROUTER_PY)
    unrouted = set(getattr(_router_mod, "UNROUTED_TYPES", ()))
    problems: list[str] = []
    for op in sorted(server_ops - router_ops):
        problems.append(
            f"server op {op!r} is not handled by the router "
            "(add it to SieveRouter._execute or reject it explicitly)"
        )
    for op in sorted(router_ops - server_ops):
        problems.append(
            f"router op {op!r} has no server-side handler "
            "(SieveService._execute does not know it)"
        )
    for t in sorted(server_types - router_types - unrouted):
        problems.append(
            f"server message type {t!r} is neither routed nor listed "
            "in router.UNROUTED_TYPES"
        )
    for t in sorted(unrouted & router_types):
        problems.append(
            f"message type {t!r} is in router.UNROUTED_TYPES but the "
            "router handles it — stale entry"
        )
    for side, ops in (("server", server_ops), ("router", router_ops)):
        if "batch" not in ops:
            problems.append(
                f"the batch op (ISSUE 14) is missing from the {side}"
            )
    for side, types in (("server", server_types),
                        ("router", router_types)):
        if "profile" not in types:
            problems.append(
                f"the profile op (ISSUE 20) is missing from the {side}"
            )
    return problems


#: every query op, exercised with both a success and (where the op can
#: fail per-request) an error-shaped call — the live parity leg replays
#: each through both encodings
_ENCODING_PROBES: tuple[dict, ...] = (
    {"op": "pi", "x": 2},
    {"op": "pi", "x": 97},
    {"op": "pi", "x": 1_999},
    {"op": "is_prime", "x": 2},
    {"op": "is_prime", "x": 91},
    {"op": "count", "lo": 10, "hi": 1_500, "kind": "primes"},
    {"op": "count", "lo": 10, "hi": 1_500, "kind": "twins"},
    {"op": "count", "lo": 900, "hi": 10, "kind": "primes"},  # error
    {"op": "nth_prime", "k": 25},
    {"op": "primes", "lo": 0, "hi": 64},
    {"op": "primes", "lo": 100, "hi": 1_900},
    {"op": "primes", "lo": 1_999, "hi": 2_000},
    {"op": "nosuch"},  # error
)


def _strip(reply: dict) -> dict:
    """Drop per-call noise (timings, trace ids) before comparison."""
    return {k: v for k, v in reply.items()
            if k not in ("id", "elapsed_ms", "t_recv", "t_sent")}


def check_encodings() -> list[str]:
    """Live parity: every op through v1 JSON and v2 binary must decode
    to identical results (and the batch of all probes member-for-member
    too). Returns mismatches; empty list means the codec is neutral."""
    import json
    import tempfile

    from sieve.config import SieveConfig
    from sieve.coordinator import run_local
    from sieve.service import ServiceClient, ServiceSettings, SieveService

    problems: list[str] = []
    with tempfile.TemporaryDirectory(prefix="wire_enc_") as tmp:
        cfg = SieveConfig(n=2_000, backend="cpu-numpy", packing="wheel30",
                          n_segments=2, quiet=True, checkpoint_dir=tmp)
        run_local(cfg)
        settings = ServiceSettings(workers=2, queue_limit=16,
                                   default_deadline_s=10.0, refresh_s=0.0)
        with SieveService(cfg, settings) as svc:
            with ServiceClient(svc.addr, timeout_s=30,
                               negotiate=False) as v1, \
                    ServiceClient(svc.addr, timeout_s=30,
                                  negotiate=True) as v2:
                if v2.wire_v < 2:
                    return ["v2 client failed to negotiate binary "
                            f"framing (got wire_v={v2.wire_v})"]
                for probe in _ENCODING_PROBES:
                    a = _strip(v1.query(**probe))
                    b = _strip(v2.query(**probe))
                    if json.dumps(a, sort_keys=True) != \
                            json.dumps(b, sort_keys=True):
                        problems.append(
                            f"encoding divergence on {probe!r}: "
                            f"v1={a!r} v2={b!r}"
                        )
                items = [dict(p) for p in _ENCODING_PROBES]
                ba = v1.query_batch(items)
                bb = v2.query_batch(items)
                if json.dumps(ba, sort_keys=True) != \
                        json.dumps(bb, sort_keys=True):
                    problems.append(
                        f"encoding divergence on the batch op: "
                        f"v1={ba!r} v2={bb!r}"
                    )
    return problems


def main(argv: list[str] | None = None) -> int:
    problems = check()
    static_n = len(problems)
    if not problems:
        # only bother booting the live service when the static surface
        # is coherent — a drift would fail the replay anyway
        problems = check_encodings()
    for p in problems:
        print(f"check_wire_ops: {p}", file=sys.stderr)
    if problems:
        print(f"check_wire_ops: FAILED ({len(problems)} "
              f"{'drift(s)' if static_n else 'encoding mismatch(es)'})",
              file=sys.stderr)
        return 1
    server_ops, server_types = harvest(SERVER_PY)
    print(
        f"check_wire_ops: ok ({len(server_ops)} ops, "
        f"{len(server_types)} message types in parity; "
        f"{len(_ENCODING_PROBES)} probes + batch byte-identical "
        "under v1 JSON and v2 binary)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
