"""Render a ``--trace`` file on the terminal: per-phase breakdown,
device-idle timeline, and the slowest spans.

The input is Chrome trace-event JSON as written by sieve/trace.py
(``{"traceEvents": [...]}``; a bare event array is accepted too), so the
same file loads in Perfetto / ``chrome://tracing`` for the visual view.

Usage: python tools/trace_report.py TRACE_FILE [--top N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_events(path_or_file) -> list[dict]:
    """Complete ("X") span events from a trace file, sorted by start."""
    if hasattr(path_or_file, "read"):
        doc = json.load(path_or_file)
    else:
        with open(path_or_file) as f:
            doc = json.load(f)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    spans = [e for e in events if e.get("ph") == "X"]
    spans.sort(key=lambda e: e["ts"])
    return spans


def phase_breakdown(spans: list[dict]) -> dict[str, dict]:
    """Aggregate spans by name: count, total/mean/max duration (us)."""
    agg: dict[str, dict] = {}
    for e in spans:
        a = agg.setdefault(
            e["name"], {"count": 0, "total_us": 0.0, "max_us": 0.0}
        )
        a["count"] += 1
        a["total_us"] += e["dur"]
        if e["dur"] > a["max_us"]:
            a["max_us"] = e["dur"]
    for a in agg.values():
        a["mean_us"] = a["total_us"] / a["count"]
    return agg


def wall_span_us(spans: list[dict]) -> float:
    if not spans:
        return 0.0
    lo = min(e["ts"] for e in spans)
    hi = max(e["ts"] + e["dur"] for e in spans)
    return hi - lo


def _fmt_args(e: dict) -> str:
    args = e.get("args")
    if not args:
        return ""
    return " " + " ".join(f"{k}={v}" for k, v in sorted(args.items()))


def report(spans: list[dict], top: int = 10) -> str:
    """The full text report (kept a pure function so tests and the
    profile_* wrappers can render without going through the CLI)."""
    lines: list[str] = []
    wall = wall_span_us(spans)
    lines.append(
        f"{len(spans)} spans over {wall / 1e3:.1f} ms of host timeline"
    )

    lines.append("")
    lines.append("per-phase breakdown (by total time):")
    lines.append(
        f"  {'phase':<24} {'count':>6} {'total ms':>10} {'mean ms':>9} "
        f"{'max ms':>9} {'% wall':>7}"
    )
    agg = phase_breakdown(spans)
    for name, a in sorted(
        agg.items(), key=lambda kv: -kv[1]["total_us"]
    ):
        pct = 100.0 * a["total_us"] / wall if wall else 0.0
        lines.append(
            f"  {name:<24} {a['count']:>6} {a['total_us'] / 1e3:>10.3f} "
            f"{a['mean_us'] / 1e3:>9.3f} {a['max_us'] / 1e3:>9.3f} "
            f"{pct:>6.1f}%"
        )

    lines.append("")
    idle = [e for e in spans if e["name"] == "round.device_idle"]
    if idle:
        total_idle = sum(e["dur"] for e in idle)
        frac = total_idle / wall if wall else 0.0
        lines.append(
            f"device-idle timeline ({len(idle)} windows, "
            f"{total_idle / 1e3:.3f} ms, {100 * frac:.1f}% of timeline):"
        )
        t0 = min(e["ts"] for e in spans)
        for e in idle:
            lines.append(
                f"  +{(e['ts'] - t0) / 1e3:>10.3f} ms  "
                f"idle {e['dur'] / 1e3:>8.3f} ms{_fmt_args(e)}"
            )
    else:
        lines.append(
            "device-idle timeline: no round.device_idle spans "
            "(device never starved, or not a mesh run)"
        )

    lines.append("")
    lines.append(f"slowest {min(top, len(spans))} spans:")
    for e in sorted(spans, key=lambda e: -e["dur"])[:top]:
        lines.append(
            f"  {e['dur'] / 1e3:>10.3f} ms  {e['name']}{_fmt_args(e)}"
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="summarize a sieve --trace file (Chrome trace-event "
        "JSON) as per-phase totals, device-idle windows, and slowest spans"
    )
    p.add_argument("trace_file")
    p.add_argument("--top", type=int, default=10,
                   help="how many slowest spans to list")
    args = p.parse_args(argv)
    spans = load_events(args.trace_file)
    if not spans:
        print("no span events in trace", file=sys.stderr)
        return 1
    print(report(spans, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
