"""Render a ``--trace`` file on the terminal: per-phase breakdown,
device-idle timeline, and the slowest spans.

The input is Chrome trace-event JSON as written by sieve/trace.py
(``{"traceEvents": [...]}``; a bare event array is accepted too), so the
same file loads in Perfetto / ``chrome://tracing`` for the visual view.

``--cluster`` renders the distributed view of a merged cpu-cluster
trace (coordinator + per-worker tracks, see sieve/cluster.py):
per-worker utilization/idle, the RPC-wait vs compute split, straggler
ranking, rpc.assign <-> worker.segment correlation/nesting after clock
rebasing, the membership timeline (worker joins/leaves and adaptive
deadline adjustments), and the per-worker clock-alignment error report.

``--routed`` renders the fleet view of a merged ROUTER trace (ISSUE 12,
see sieve/service/router.py): router ``rpc.route`` spans correlated by
trace-context prefix with the shard-replica ``rpc.query`` children
merged under per-replica tracks, plus each replica's clock-alignment
error bound.

``--bundle`` renders a flight-recorder postmortem bundle (ISSUE 13, see
sieve/debug.py) instead of a trace: what tripped the trigger, metric
sparklines over the bundled history window, the span-ring tail, and the
last error-ish events — for a single-process ``bundle.json`` or a
merged ``fleet_bundle.json`` from tools/fleet_debug.py (a directory is
accepted and searched for either file).

A file that is not valid trace JSON (truncated write, wrong file, a
bare object without ``traceEvents``) exits 1 with a named
``trace_report: error:`` line instead of a traceback.

Usage: python tools/trace_report.py TRACE_FILE [--top N]
       [--cluster | --routed | --bundle]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class TraceLoadError(Exception):
    """The input is not a readable Chrome trace-event file."""


def load_all(path_or_file) -> list[dict]:
    """Every event in a trace file (spans, instants, counters, metadata).

    Raises :class:`TraceLoadError` — never a bare decode traceback — on
    a missing/unreadable file, malformed or truncated JSON, or JSON of
    the wrong shape (satellite: tooling must fail named, not crash)."""
    name = getattr(path_or_file, "name", str(path_or_file))
    try:
        if hasattr(path_or_file, "read"):
            doc = json.load(path_or_file)
        else:
            with open(path_or_file) as f:
                doc = json.load(f)
    except json.JSONDecodeError as e:
        raise TraceLoadError(
            f"{name}: malformed or truncated trace JSON ({e})"
        ) from None
    except UnicodeDecodeError:
        raise TraceLoadError(f"{name}: not a text JSON file") from None
    except OSError as e:
        raise TraceLoadError(f"{name}: {e.strerror or e}") from None
    if isinstance(doc, dict):
        if "traceEvents" not in doc:
            raise TraceLoadError(
                f"{name}: JSON object has no 'traceEvents' key — not a "
                "Chrome trace-event file"
            )
        doc = doc["traceEvents"]
    if not isinstance(doc, list) or any(
        not isinstance(e, dict) for e in doc
    ):
        raise TraceLoadError(
            f"{name}: trace events must be a list of objects"
        )
    return doc


def load_events(path_or_file) -> list[dict]:
    """Complete ("X") span events from a trace file, sorted by start."""
    spans = [e for e in load_all(path_or_file) if e.get("ph") == "X"]
    spans.sort(key=lambda e: e["ts"])
    return spans


def phase_breakdown(spans: list[dict]) -> dict[str, dict]:
    """Aggregate spans by name: count, total/mean/max duration (us)."""
    agg: dict[str, dict] = {}
    for e in spans:
        a = agg.setdefault(
            e["name"], {"count": 0, "total_us": 0.0, "max_us": 0.0}
        )
        a["count"] += 1
        a["total_us"] += e["dur"]
        if e["dur"] > a["max_us"]:
            a["max_us"] = e["dur"]
    for a in agg.values():
        a["mean_us"] = a["total_us"] / a["count"]
    return agg


def wall_span_us(spans: list[dict]) -> float:
    if not spans:
        return 0.0
    lo = min(e["ts"] for e in spans)
    hi = max(e["ts"] + e["dur"] for e in spans)
    return hi - lo


def _fmt_args(e: dict) -> str:
    args = e.get("args")
    if not args:
        return ""
    return " " + " ".join(f"{k}={v}" for k, v in sorted(args.items()))


def report(spans: list[dict], top: int = 10) -> str:
    """The full text report (kept a pure function so tests and the
    profile_* wrappers can render without going through the CLI)."""
    lines: list[str] = []
    wall = wall_span_us(spans)
    lines.append(
        f"{len(spans)} spans over {wall / 1e3:.1f} ms of host timeline"
    )

    lines.append("")
    lines.append("per-phase breakdown (by total time):")
    lines.append(
        f"  {'phase':<24} {'count':>6} {'total ms':>10} {'mean ms':>9} "
        f"{'max ms':>9} {'% wall':>7}"
    )
    agg = phase_breakdown(spans)
    for name, a in sorted(
        agg.items(), key=lambda kv: -kv[1]["total_us"]
    ):
        pct = 100.0 * a["total_us"] / wall if wall else 0.0
        lines.append(
            f"  {name:<24} {a['count']:>6} {a['total_us'] / 1e3:>10.3f} "
            f"{a['mean_us'] / 1e3:>9.3f} {a['max_us'] / 1e3:>9.3f} "
            f"{pct:>6.1f}%"
        )

    lines.append("")
    idle = [e for e in spans if e["name"] == "round.device_idle"]
    if idle:
        total_idle = sum(e["dur"] for e in idle)
        frac = total_idle / wall if wall else 0.0
        lines.append(
            f"device-idle timeline ({len(idle)} windows, "
            f"{total_idle / 1e3:.3f} ms, {100 * frac:.1f}% of timeline):"
        )
        t0 = min(e["ts"] for e in spans)
        for e in idle:
            lines.append(
                f"  +{(e['ts'] - t0) / 1e3:>10.3f} ms  "
                f"idle {e['dur'] / 1e3:>8.3f} ms{_fmt_args(e)}"
            )
    else:
        lines.append(
            "device-idle timeline: no round.device_idle spans "
            "(device never starved, or not a mesh run)"
        )

    service = service_report(spans)
    if service:
        lines.append("")
        lines.extend(service)

    router = router_report(spans)
    if router:
        lines.append("")
        lines.extend(router)

    lines.append("")
    lines.append(f"slowest {min(top, len(spans))} spans:")
    for e in sorted(spans, key=lambda e: -e["dur"])[:top]:
        lines.append(
            f"  {e['dur'] / 1e3:>10.3f} ms  {e['name']}{_fmt_args(e)}"
        )
    return "\n".join(lines)


def service_report(spans: list[dict]) -> list[str]:
    """Query-service section: per-request latency decomposed into
    queue-wait vs index materialization vs cold backend compute, split
    by reply source (sieve/service/ rpc.query spans). Empty when the
    trace has no service traffic."""
    rpc = [e for e in spans if e["name"] == "rpc.query"]
    refreshes = [e for e in spans if e["name"] == "service.refresh"]
    if not rpc and not refreshes:
        return []
    lines = ["query service (rpc.query requests):"]
    if refreshes:
        # live-follow freshness (ISSUE 8): how often the snapshot swapped,
        # how often a refresh was skipped, and how stale the last swapped
        # snapshot is at the end of the trace
        swapped = [e for e in refreshes
                   if e.get("args", {}).get("outcome") == "swapped"]
        failed = len(refreshes) - len(swapped)
        trace_end = max(e["ts"] + e["dur"] for e in spans)
        if swapped:
            last = max(swapped, key=lambda e: e["ts"])
            staleness_s = (trace_end - (last["ts"] + last["dur"])) / 1e6
            lines.append(
                f"  ledger follow: {len(swapped)} refresh(es) swapped, "
                f"{failed} skipped; covered_hi="
                f"{last.get('args', {}).get('covered_hi', '?')}, snapshot "
                f"{staleness_s:.3f}s stale at trace end"
            )
        else:
            lines.append(
                f"  ledger follow: 0 refreshes swapped, {failed} skipped "
                "(serving the startup snapshot)"
            )
    if not rpc:
        return lines
    by_outcome: dict[tuple[str, str], list[float]] = {}
    for e in rpc:
        a = e.get("args", {})
        key = (str(a.get("op", "?")), str(a.get("outcome", "?")),
               str(a.get("source", "?")))
        by_outcome.setdefault(key, []).append(e["dur"])
    lines.append(
        f"  {'op':<10} {'outcome':<18} {'source':<7} {'count':>6} "
        f"{'total ms':>10} {'mean ms':>9} {'max ms':>9}"
    )
    for (op, outcome, source), durs in sorted(
        by_outcome.items(), key=lambda kv: -sum(kv[1])
    ):
        lines.append(
            f"  {op:<10} {outcome:<18} {source:<7} {len(durs):>6} "
            f"{sum(durs) / 1e3:>10.3f} {sum(durs) / len(durs) / 1e3:>9.3f} "
            f"{max(durs) / 1e3:>9.3f}"
        )
    total = sum(e["dur"] for e in rpc)
    parts = [
        ("queue-wait", "query.queue_wait"),
        ("index materialize", "query.materialize"),
        ("cold compute", "query.cold"),
    ]
    lines.append(
        f"  latency split over {len(rpc)} requests "
        f"({total / 1e3:.3f} ms total in rpc.query):"
    )
    accounted = 0.0
    for label, name in parts:
        t = sum(e["dur"] for e in spans if e["name"] == name)
        accounted += t
        pct = 100 * t / total if total else 0.0
        lines.append(f"    {label:<18} {t / 1e3:>10.3f} ms {pct:>6.1f}%")
    # batched cold plane (ISSUE 9): query.cold spans nest inside
    # query.cold_batch, so the batch row reports only the drain/dispatch
    # overhead on top of the compute already counted above
    batches = [e for e in spans if e["name"] == "query.cold_batch"]
    if batches:
        cold_t = sum(e["dur"] for e in spans if e["name"] == "query.cold")
        over = max(0.0, sum(e["dur"] for e in batches) - cold_t)
        accounted += over
        chunks = sum((e.get("args") or {}).get("chunks", 0) for e in batches)
        lines.append(
            f"    {'cold batch':<18} {over / 1e3:>10.3f} ms "
            f"{100 * over / total if total else 0:>6.1f}%"
            f"  ({len(batches)} dispatches, {chunks} chunks)"
        )
    # mesh cold plane (ISSUE 18): query.cold_mesh spans nest inside
    # query.cold, so this row is informational (NOT added to accounted
    # — that would double-count) — it shows how much of the cold compute
    # ran as one-launch SPMD rounds and at what chunk fanout
    mesh = [e for e in spans if e["name"] == "query.cold_mesh"]
    if mesh:
        mesh_t = sum(e["dur"] for e in mesh)
        chunks = sum((e.get("args") or {}).get("chunks", 0) for e in mesh)
        devices = max(
            (e.get("args") or {}).get("devices", 0) for e in mesh
        )
        lines.append(
            f"    {'cold mesh':<18} {mesh_t / 1e3:>10.3f} ms "
            f"{100 * mesh_t / total if total else 0:>6.1f}%"
            f"  ({len(mesh)} SPMD launches, {chunks} chunks, "
            f"{devices} devices; nested in cold compute)"
        )
    other = max(0.0, total - accounted)
    lines.append(
        f"    {'index/other':<18} {other / 1e3:>10.3f} ms "
        f"{100 * other / total if total else 0:>6.1f}%"
    )
    # priority lanes (ISSUE 10): per-lane request latency + queue wait.
    # Traces from pre-lane servers carry no lane arg and skip the block.
    by_lane: dict[str, list[float]] = {}
    for e in rpc:
        lane = (e.get("args") or {}).get("lane")
        if lane is not None:
            by_lane.setdefault(str(lane), []).append(e["dur"])
    if by_lane:
        waits: dict[str, list[float]] = {}
        for e in spans:
            if e["name"] != "query.queue_wait":
                continue
            lane = (e.get("args") or {}).get("lane")
            if lane is not None:
                waits.setdefault(str(lane), []).append(e["dur"])
        lines.append(
            f"  {'lane':<6} {'count':>6} {'mean ms':>9} {'p95 ms':>9} "
            f"{'max ms':>9} {'wait p95 ms':>12}"
        )
        for lane in sorted(by_lane):
            durs = sorted(by_lane[lane])
            p95 = durs[max(0, math.ceil(0.95 * len(durs)) - 1)]
            w = sorted(waits.get(lane, []))
            # no observations is "-", never a fake 0.0 percentile
            wp95 = w[max(0, math.ceil(0.95 * len(w)) - 1)] if w else None
            wp95_s = f"{wp95 / 1e3:>12.3f}" if wp95 is not None \
                else f"{'-':>12}"
            lines.append(
                f"  {lane:<6} {len(durs):>6} "
                f"{sum(durs) / len(durs) / 1e3:>9.3f} {p95 / 1e3:>9.3f} "
                f"{max(durs) / 1e3:>9.3f} {wp95_s}"
            )
    return lines


def router_report(spans: list[dict]) -> list[str]:
    """Shard-router section (ISSUE 11): front-door latency by op and
    outcome (``rpc.route`` spans) plus the per-shard scatter table
    (``route.scatter`` spans — one per downstream shard call, so a
    scatter-gather query contributes a row to several shards). Traces
    from pre-router runs have no rpc.route spans and skip the block."""
    route = [e for e in spans if e["name"] == "rpc.route"]
    if not route:
        return []
    lines = ["shard router (rpc.route requests):"]
    by_key: dict[tuple[str, str], list[float]] = {}
    fanout = 0
    for e in route:
        a = e.get("args", {})
        by_key.setdefault(
            (str(a.get("op", "?")), str(a.get("outcome", "?"))), []
        ).append(e["dur"])
        fanout += int(a.get("shards", 0) or 0)
    lines.append(
        f"  {len(route)} routed requests, "
        f"{fanout / len(route):.2f} shards touched per request"
    )
    lines.append(
        f"  {'op':<10} {'outcome':<18} {'count':>6} {'total ms':>10} "
        f"{'mean ms':>9} {'max ms':>9}"
    )
    for (op, outcome), durs in sorted(
        by_key.items(), key=lambda kv: -sum(kv[1])
    ):
        lines.append(
            f"  {op:<10} {outcome:<18} {len(durs):>6} "
            f"{sum(durs) / 1e3:>10.3f} {sum(durs) / len(durs) / 1e3:>9.3f} "
            f"{max(durs) / 1e3:>9.3f}"
        )
    scatter = [e for e in spans if e["name"] == "route.scatter"]
    if scatter:
        by_shard: dict[str, dict] = {}
        for e in scatter:
            a = e.get("args", {})
            row = by_shard.setdefault(
                str(a.get("shard", "?")), {"durs": [], "outcomes": {}}
            )
            row["durs"].append(e["dur"])
            o = str(a.get("outcome", "?"))
            row["outcomes"][o] = row["outcomes"].get(o, 0) + 1
        lines.append(
            f"  per-shard scatter ({len(scatter)} downstream calls):"
        )
        lines.append(
            f"  {'shard':<6} {'calls':>6} {'mean ms':>9} {'p95 ms':>9} "
            f"{'max ms':>9}  outcomes"
        )
        for shard in sorted(by_shard, key=lambda s: (len(s), s)):
            row = by_shard[shard]
            durs = sorted(row["durs"])
            p95 = durs[max(0, math.ceil(0.95 * len(durs)) - 1)]
            outs = " ".join(
                f"{k}={v}" for k, v in sorted(row["outcomes"].items())
            )
            lines.append(
                f"  {shard:<6} {len(durs):>6} "
                f"{sum(durs) / len(durs) / 1e3:>9.3f} {p95 / 1e3:>9.3f} "
                f"{max(durs) / 1e3:>9.3f}  {outs}"
            )
    return lines


def routed_report(events: list[dict]) -> str:
    """The fleet view of a merged router trace (pure function, ISSUE 12).

    Expects the stream a tracing :class:`~sieve.service.router.SieveRouter`
    writes: its own ``rpc.route``/``route.scatter`` spans plus per-shard-
    replica tracks (``process_name`` = ``"shard<i> <addr>"``) carrying the
    rebased ``rpc.query`` (and queue-wait/cold) children shipped back on
    reply piggybacks, with one ``clock.align`` instant per merge.

    Correlation is by trace-context prefix: a shard ``rpc.query`` with
    ``args.ctx = R/s1.3.0`` is a child of the ``rpc.route`` whose
    ``args.ctx = R``. Point queries have exactly one child; scatters have
    one per shard touched."""
    spans = sorted(
        (e for e in events if e.get("ph") == "X"), key=lambda e: e["ts"]
    )
    replica_pids = {
        e["pid"]: e["args"]["name"]
        for e in events
        if e.get("ph") == "M"
        and e.get("name") == "process_name"
        and str(e.get("args", {}).get("name", "")).startswith("shard")
    }
    route = [e for e in spans if e["name"] == "rpc.route"]
    if not route:
        return (
            "no rpc.route spans in trace — not a router trace "
            "(python -m sieve route with --trace)"
        )
    if not replica_pids:
        return (
            "no shard-replica tracks in trace — shards did not piggyback "
            "telemetry (start them with SIEVE_SVC_TELEMETRY=1), or every "
            "payload was dropped"
        )
    lines: list[str] = []
    wall = wall_span_us(spans)
    queries = [
        e for e in spans
        if e["name"] == "rpc.query" and e.get("pid") in replica_pids
    ]
    lines.append(
        f"routed-query fleet timeline: {len(replica_pids)} shard-replica "
        f"tracks, {len(route)} rpc.route spans, {len(queries)} merged "
        f"shard rpc.query spans over {wall / 1e3:.1f} ms"
    )

    # --- route -> child correlation by ctx prefix ---------------------------
    by_ctx: dict[str, list[dict]] = {}
    for q in queries:
        ctx = str(q.get("args", {}).get("ctx", ""))
        if ctx:
            # child ctx "R/s<i>.<call>.<attempt>" -> route ctx "R"
            base = ctx.rsplit("/", 1)[0]
            by_ctx.setdefault(base, []).append(q)
    correlated = exactly_one = nested = 0
    for r in route:
        rctx = str(r.get("args", {}).get("ctx", ""))
        kids = by_ctx.get(rctx, []) if rctx else []
        if not kids:
            continue
        correlated += 1
        if len(kids) == 1:
            exactly_one += 1
        if all(
            k["ts"] >= r["ts"]
            and k["ts"] + k["dur"] <= r["ts"] + r["dur"]
            for k in kids
        ):
            nested += 1
    lines.append(
        f"correlation: {correlated}/{len(route)} rpc.route spans have "
        f"shard rpc.query children "
        f"({100 * correlated / len(route):.1f}%); "
        f"{exactly_one} with exactly one child; nested after rebase: "
        f"{nested}/{correlated} "
        f"({100 * nested / correlated if correlated else 0:.1f}%)"
    )

    # --- per-replica tracks -------------------------------------------------
    lines.append("")
    lines.append("per-replica tracks (merged rpc.query spans):")
    lines.append(
        f"  {'replica':<28} {'spans':>6} {'mean ms':>9} {'p95 ms':>9} "
        f"{'max ms':>9}"
    )
    for pid in sorted(replica_pids):
        durs = sorted(e["dur"] for e in queries if e.get("pid") == pid)
        if durs:
            p95 = durs[max(0, math.ceil(0.95 * len(durs)) - 1)]
            lines.append(
                f"  {replica_pids[pid]:<28} {len(durs):>6} "
                f"{sum(durs) / len(durs) / 1e3:>9.3f} {p95 / 1e3:>9.3f} "
                f"{durs[-1] / 1e3:>9.3f}"
            )
        else:
            lines.append(
                f"  {replica_pids[pid]:<28} {0:>6} {'-':>9} {'-':>9} "
                f"{'-':>9}"
            )

    # --- clock alignment ----------------------------------------------------
    lines.append("")
    aligns = [
        e for e in events
        if e.get("name") == "clock.align"
        and "replica" in e.get("args", {})
    ]
    if aligns:
        lines.append("per-replica clock alignment (min-RTT estimate, "
                     "error bound = RTT/2):")
        latest: dict[str, dict] = {}
        for e in sorted(aligns, key=lambda e: e.get("ts", 0)):
            latest[str(e["args"]["replica"])] = e["args"]
        max_err = None
        total_dropped = 0
        for rep in sorted(latest):
            a = latest[rep]
            total_dropped += a.get("dropped", 0)
            if "offset_s" in a:
                max_err = (
                    a["err_s"] if max_err is None
                    else max(max_err, a["err_s"])
                )
                lines.append(
                    f"  shard{a.get('shard', '?')} {rep}: offset "
                    f"{a['offset_s'] * 1e3:+.3f} ms, rtt "
                    f"{a['rtt_s'] * 1e3:.3f} ms, err <= "
                    f"{a['err_s'] * 1e6:.0f} us "
                    f"({a.get('samples', 0)} samples, "
                    f"{a.get('dropped', 0)} events dropped)"
                )
            else:
                lines.append(
                    f"  shard{a.get('shard', '?')} {rep}: no alignment "
                    "sample (events merged unrebased)"
                )
        if max_err is not None:
            lines.append(
                f"  max clock-alignment error: {max_err * 1e6:.0f} us"
            )
        if total_dropped:
            lines.append(
                f"  WARNING: {total_dropped} shard trace events dropped "
                "by the ship ring (raise SIEVE_TELEMETRY_RING)"
            )
    else:
        lines.append("clock alignment: no replica clock.align events "
                     "in trace")
    return "\n".join(lines)


def cluster_report(events: list[dict], top: int = 10) -> str:
    """The distributed view of a merged cluster trace (pure function).

    Expects the event stream written by a cpu-cluster ``--trace`` run:
    coordinator ``rpc.assign`` spans, per-worker process tracks
    (``process_name`` = "worker N") carrying the rebased
    ``worker.recv``/``worker.segment``/``worker.reply`` spans, and one
    ``clock.align`` instant per worker.
    """
    spans = sorted(
        (e for e in events if e.get("ph") == "X"), key=lambda e: e["ts"]
    )
    worker_pids = {
        e["pid"]: e["args"]["name"]
        for e in events
        if e.get("ph") == "M"
        and e.get("name") == "process_name"
        and str(e.get("args", {}).get("name", "")).startswith("worker ")
    }
    if not worker_pids:
        return (
            "no worker tracks in trace — not a merged cluster trace "
            "(cpu-cluster backend with --trace), or no worker shipped "
            "telemetry"
        )
    lines: list[str] = []
    wall = wall_span_us(spans)
    rpc = [e for e in spans if e["name"] == "rpc.assign"]
    wseg = [e for e in spans if e["name"] == "worker.segment"]
    lines.append(
        f"cluster timeline: {len(worker_pids)} workers, {len(rpc)} "
        f"rpc.assign round-trips over {wall / 1e3:.1f} ms"
    )

    # --- per-worker utilization / idle --------------------------------------
    lines.append("")
    lines.append("per-worker utilization (busy = worker.segment time, "
                 "idle = worker.recv wait):")
    lines.append(
        f"  {'worker':<10} {'segs':>5} {'busy ms':>10} {'util %':>7} "
        f"{'idle ms':>10} {'idle %':>7} {'reply ms':>9}"
    )
    per_worker: dict[int, dict] = {}
    for pid in worker_pids:
        rows = [e for e in spans if e["pid"] == pid]
        busy = sum(e["dur"] for e in rows if e["name"] == "worker.segment")
        idle = sum(e["dur"] for e in rows if e["name"] == "worker.recv")
        reply = sum(e["dur"] for e in rows if e["name"] == "worker.reply")
        segs = [e for e in rows if e["name"] == "worker.segment"]
        per_worker[pid] = {
            "busy": busy, "idle": idle, "segs": segs,
            "max_seg": max((e["dur"] for e in segs), default=0.0),
        }
        lines.append(
            f"  {worker_pids[pid]:<10} {len(segs):>5} {busy / 1e3:>10.3f} "
            f"{100 * busy / wall if wall else 0:>6.1f}% "
            f"{idle / 1e3:>10.3f} "
            f"{100 * idle / wall if wall else 0:>6.1f}% {reply / 1e3:>9.3f}"
        )

    # --- rpc-wait vs compute split ------------------------------------------
    # correlate by trace context: each rpc.assign and the worker.segment
    # of the same attempt share args.ctx
    seg_by_ctx = {
        e["args"]["ctx"]: e
        for e in wseg
        if e.get("args", {}).get("ctx")
    }
    corr = nested = 0
    rpc_total = seg_total = 0.0
    for r in rpc:
        rpc_total += r["dur"]
        w = seg_by_ctx.get(r.get("args", {}).get("ctx"))
        if w is None:
            continue
        corr += 1
        seg_total += w["dur"]
        if (
            w["ts"] >= r["ts"]
            and w["ts"] + w["dur"] <= r["ts"] + r["dur"]
        ):
            nested += 1
    lines.append("")
    wait = max(0.0, rpc_total - seg_total)
    lines.append(
        f"rpc-wait vs compute (over {corr} correlated round-trips): "
        f"compute {seg_total / 1e3:.3f} ms "
        f"({100 * seg_total / rpc_total if rpc_total else 0:.1f}%), "
        f"rpc-wait {wait / 1e3:.3f} ms "
        f"({100 * wait / rpc_total if rpc_total else 0:.1f}%)"
    )
    lines.append(
        f"correlation: {corr}/{len(rpc)} rpc.assign spans have a "
        f"worker.segment child; nested after rebase: {nested}/{corr} "
        f"({100 * nested / corr if corr else 0:.1f}%)"
    )

    # --- straggler ranking ---------------------------------------------------
    lines.append("")
    lines.append("straggler ranking (by slowest single segment):")
    ranked = sorted(
        per_worker.items(), key=lambda kv: -kv[1]["max_seg"]
    )[:top]
    for pid, w in ranked:
        n = len(w["segs"])
        mean = w["busy"] / n if n else 0.0
        lines.append(
            f"  {worker_pids[pid]:<10} max {w['max_seg'] / 1e3:>9.3f} ms  "
            f"mean {mean / 1e3:>9.3f} ms  busy {w['busy'] / 1e3:>9.3f} ms"
        )

    # --- membership timeline -------------------------------------------------
    membership = sorted(
        (
            e for e in events
            if e.get("ph") == "i" and e.get("name") in (
                "cluster.worker_joined", "cluster.worker_left",
                "cluster.deadline_adjusted",
            )
        ),
        key=lambda e: e.get("ts", 0),
    )
    if membership:
        lines.append("")
        lines.append("membership timeline (joins, leaves, deadline "
                     "adjustments):")
        t0 = min(e["ts"] for e in spans) if spans else membership[0]["ts"]
        for e in membership:
            a = e.get("args", {})
            if e["name"] == "cluster.worker_joined":
                what = (
                    f"worker {a.get('worker')} joined "
                    f"(active={a.get('active')})"
                )
            elif e["name"] == "cluster.worker_left":
                what = (
                    f"worker {a.get('worker')} left "
                    f"(active={a.get('active')})"
                )
            else:
                prev = a.get("prev_s")
                what = (
                    f"deadline adjusted to {a.get('deadline_s')}s"
                    + (f" (was {prev}s)" if prev is not None else "")
                )
            lines.append(f"  +{(e['ts'] - t0) / 1e3:>10.3f} ms  {what}")

    # --- clock alignment -----------------------------------------------------
    lines.append("")
    aligns = [e for e in events if e.get("name") == "clock.align"]
    if aligns:
        lines.append("clock alignment (NTP-style min-RTT estimate, "
                     "error bound = RTT/2):")
        max_err = None
        total_dropped = 0
        for e in sorted(aligns, key=lambda e: e["args"].get("worker", 0)):
            a = e["args"]
            total_dropped += a.get("dropped", 0)
            if "offset_s" in a:
                max_err = (
                    a["err_s"] if max_err is None
                    else max(max_err, a["err_s"])
                )
                lines.append(
                    f"  worker {a['worker']}: offset "
                    f"{a['offset_s'] * 1e3:+.3f} ms, rtt "
                    f"{a['rtt_s'] * 1e3:.3f} ms, err <= "
                    f"{a['err_s'] * 1e6:.0f} us "
                    f"({a.get('samples', 0)} samples, "
                    f"{a.get('dropped', 0)} events dropped)"
                )
            else:
                lines.append(
                    f"  worker {a['worker']}: no alignment sample "
                    f"(events merged unrebased)"
                )
        if max_err is not None:
            lines.append(
                f"  max clock-alignment error: {max_err * 1e6:.0f} us"
            )
        if total_dropped:
            lines.append(
                f"  WARNING: {total_dropped} worker trace events dropped "
                "by the ship ring (raise SIEVE_TELEMETRY_RING)"
            )
    else:
        lines.append("clock alignment: no clock.align events in trace")
    return "\n".join(lines)


# --- flight-recorder bundles (ISSUE 13) ---------------------------------

_SPARK = "▁▂▃▄▅▆▇█"
_BUNDLE_PREFIX = "sieve-debug/"
_FLEET_PREFIX = "sieve-fleet-debug/"


def load_bundle(path: str) -> dict:
    """A flight-recorder bundle document from a file or a bundle dir.

    Accepts a ``bundle.json`` / ``fleet_bundle.json`` path directly, or
    a directory that contains either. Raises :class:`TraceLoadError`
    (named, no traceback) on anything that is not a recorder bundle."""
    if os.path.isdir(path):
        for name in ("fleet_bundle.json", "bundle.json"):
            cand = os.path.join(path, name)
            if os.path.exists(cand):
                path = cand
                break
        else:
            raise TraceLoadError(
                f"{path}: directory holds no fleet_bundle.json or "
                "bundle.json"
            )
    try:
        with open(path) as f:
            doc = json.load(f)
    except json.JSONDecodeError as e:
        raise TraceLoadError(
            f"{path}: malformed or truncated bundle JSON ({e})"
        ) from None
    except UnicodeDecodeError:
        raise TraceLoadError(f"{path}: not a text JSON file") from None
    except OSError as e:
        raise TraceLoadError(f"{path}: {e.strerror or e}") from None
    ver = doc.get("bundle") if isinstance(doc, dict) else None
    if not isinstance(ver, str) or not ver.startswith(
        (_BUNDLE_PREFIX, _FLEET_PREFIX)
    ):
        raise TraceLoadError(
            f"{path}: no recognised 'bundle' version key — not a "
            "flight-recorder bundle (see sieve/debug.py)"
        )
    return doc


def _sparkline(vals: list) -> str:
    pts = [float(v) for v in vals
           if isinstance(v, (int, float)) and math.isfinite(v)]
    if not pts:
        return "-"
    lo, hi = min(pts), max(pts)
    if hi <= lo:
        return _SPARK[0] * len(pts)
    scale = (len(_SPARK) - 1) / (hi - lo)
    return "".join(_SPARK[int((v - lo) * scale + 0.5)] for v in pts)


def _history_series(history: list) -> dict[str, list]:
    """name -> per-sample numeric series across a bundle's history rows.

    Counters/gauges contribute their ``value``; histograms their
    ``count``. A metric absent from an older row pads with None so every
    series spans the same sample axis."""
    names: list[str] = []
    seen: set[str] = set()
    for row in history:
        for name, inst in (row.get("metrics") or {}).items():
            if name not in seen and isinstance(inst, dict):
                seen.add(name)
                names.append(name)
    series: dict[str, list] = {n: [] for n in names}
    for row in history:
        snap = row.get("metrics") or {}
        for n in names:
            inst = snap.get(n)
            if not isinstance(inst, dict):
                series[n].append(None)
            elif "value" in inst:
                series[n].append(inst["value"])
            else:
                series[n].append(inst.get("count"))
    return series


def _compact(d: dict, skip: tuple = ("event", "ts")) -> str:
    parts = [f"{k}={d[k]!r}" for k in d if k not in skip]
    s = " ".join(parts)
    return s if len(s) <= 72 else s[:69] + "..."


def _one_bundle_lines(b: dict, max_series: int = 12,
                      span_tail: int = 15, error_tail: int = 10) -> list:
    lines = [
        f"  role={b.get('role')} pid={b.get('pid')} "
        f"wall={b.get('wall_time')}",
        f"  trigger: {b.get('trigger')}"
        + (f"  detail: {json.dumps(b.get('detail'))}"
           if b.get("detail") else ""),
    ]
    if b.get("path"):
        lines.append(f"  written: {b['path']}")
    rec = b.get("recorder") or {}
    lines.append(
        f"  recorder: {rec.get('bundles', 0)} bundles, "
        f"{rec.get('suppressed', 0)} suppressed by cooldown, "
        f"{b.get('spans_dropped', 0)} spans dropped by ring"
    )
    history = b.get("history") or []
    series = _history_series(history)
    if series:
        lines.append(f"  metrics history ({len(history)} samples):")
        shown = 0
        for name, vals in series.items():
            if shown >= max_series:
                lines.append(
                    f"    ... {len(series) - shown} more series"
                )
                break
            last = next((v for v in reversed(vals) if v is not None), None)
            lines.append(
                f"    {name:<38} last={last!r:>10}  {_sparkline(vals)}"
            )
            shown += 1
    else:
        lines.append("  metrics history: no samples (sampler disabled?)")
    spans = b.get("spans") or []
    if spans:
        lines.append(f"  span tail (last {min(span_tail, len(spans))} "
                     f"of {len(spans)}):")
        for s in spans[-span_tail:]:
            dur = s.get("dur")
            dur_ms = f"{dur / 1e3:.3f} ms" if dur is not None else "-"
            lines.append(f"    {s.get('name', '?'):<28} {dur_ms:>12}")
    errors = b.get("errors") or []
    if errors:
        lines.append(f"  last errors ({len(errors)}):")
        for e in errors[-error_tail:]:
            lines.append(f"    {e.get('event', '?'):<24} {_compact(e)}")
    else:
        lines.append("  last errors: none recorded")
    prof = b.get("profile")
    if prof:
        from sieve.profile import self_times

        merged = {r["stack"]: {"count": r["count"],
                               "role": r.get("role")}
                  for r in prof.get("stacks") or []}
        lines.append(
            f"  profile ({prof.get('hz')} Hz, "
            f"{prof.get('samples', 0)} samples, "
            f"{len(prof.get('stacks') or [])} stacks, "
            f"{prof.get('evicted', 0)} evicted) — top self-time:"
        )
        for r in self_times(merged, 8):
            lines.append(
                f"    {r['frame']:<38} {r['self']:>6}  {r['share']:.1%}"
            )
    return lines


def bundle_report(doc: dict) -> str:
    """Terminal postmortem of a flight-recorder bundle (pure function).

    Handles both a single-process bundle and a merged fleet bundle from
    tools/fleet_debug.py."""
    ver = doc.get("bundle", "")
    lines: list[str] = []
    if ver.startswith(_FLEET_PREFIX):
        reps = doc.get("replicas") or []
        lines.append(
            f"fleet debug bundle ({ver}): "
            f"{doc.get('processes', 0)} processes captured"
        )
        router = doc.get("router") or {}
        lines.append("")
        if router.get("bundle"):
            lines.append(f"router {router.get('addr', '?')}")
            lines.extend(_one_bundle_lines(router["bundle"]))
        else:
            lines.append(
                f"router {router.get('addr', '?')}: NO BUNDLE "
                f"({router.get('error')})"
            )
        for rep in reps:
            tag = (f"s{rep['shard']} " if rep.get("shard") is not None
                   else "")
            lines.append("")
            if rep.get("bundle"):
                lines.append(f"replica {tag}{rep.get('addr', '?')}")
                lines.extend(_one_bundle_lines(rep["bundle"]))
            else:
                lines.append(
                    f"replica {tag}{rep.get('addr', '?')}: NO BUNDLE "
                    f"({rep.get('error')})"
                )
        return "\n".join(lines)
    lines.append(f"debug bundle ({ver})")
    lines.extend(_one_bundle_lines(doc))
    return "\n".join(lines)


# --- tail-sampled exemplars (ISSUE 19) ----------------------------------


def load_exemplar_file(path: str) -> list[dict]:
    """Exemplar records from an ``exemplars.jsonl`` path or a debug dir
    holding one (rotated ``.1`` generation included, oldest first)."""
    from sieve.service.exemplar import EXEMPLAR_FILE, load_exemplars

    if os.path.isdir(path):
        path = os.path.join(path, EXEMPLAR_FILE)
    out: list[dict] = []
    if os.path.exists(path + ".1"):
        out.extend(load_exemplars(path + ".1"))
    try:
        out.extend(load_exemplars(path))
    except OSError as e:
        if not out:
            raise TraceLoadError(f"{path}: {e.strerror or e}") from None
    if not out:
        raise TraceLoadError(f"{path}: no exemplar records")
    return out


def exemplar_report(recs: list[dict], top: int = 10) -> str:
    """Terminal rendering of kept exemplars (pure function): retention
    breakdown, kept-latency sparkline, then the ``top`` slowest kept
    requests with their span trees and downstream shard exemplars."""
    by_reason: dict[str, int] = {}
    by_outcome: dict[str, int] = {}
    for r in recs:
        by_reason[r.get("reason", "?")] = by_reason.get(
            r.get("reason", "?"), 0) + 1
        by_outcome[r.get("outcome", "?")] = by_outcome.get(
            r.get("outcome", "?"), 0) + 1
    lines = [
        f"exemplars: {len(recs)} kept",
        "  by reason:  " + "  ".join(
            f"{k}={v}" for k, v in sorted(by_reason.items())),
        "  by outcome: " + "  ".join(
            f"{k}={v}" for k, v in sorted(by_outcome.items())),
        "  kept latency (ms, keep order): "
        + _sparkline([r.get("ms") for r in recs]),
    ]
    slow = sorted(recs, key=lambda r: r.get("ms") or 0.0,
                  reverse=True)[:top]
    lines.append(f"  slowest {len(slow)} kept:")
    for r in slow:
        tag = (f"[{r.get('role', '?')}] {r.get('op', '?'):<10} "
               f"{(r.get('ms') or 0.0):>9.3f} ms  "
               f"reason={r.get('reason')} outcome={r.get('outcome')}")
        if r.get("ctx"):
            tag += f"  ctx={r['ctx']}"
        if r.get("shards") is not None:
            tag += f"  shards={r['shards']}"
        lines.append(f"    {tag}")
        for s in (r.get("spans") or [])[-8:]:
            dur = s.get("dur")
            dur_ms = f"{dur / 1e3:.3f} ms" if dur is not None else "-"
            lines.append(f"      {s.get('name', '?'):<28} {dur_ms:>12}")
        for d in r.get("downstream") or []:
            lines.append(
                f"      ↳ shard {d.get('shard', '?')} "
                f"{d.get('addr', '?')}: {d.get('op', '?')} "
                f"{(d.get('ms') or 0.0):.3f} ms "
                f"reason={d.get('reason')} "
                f"spans={len(d.get('spans') or ())}"
            )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="summarize a sieve --trace file (Chrome trace-event "
        "JSON) as per-phase totals, device-idle windows, and slowest spans"
    )
    p.add_argument("trace_file")
    p.add_argument("--top", type=int, default=10,
                   help="how many slowest spans to list")
    p.add_argument("--cluster", action="store_true",
                   help="distributed view of a merged cpu-cluster trace: "
                        "per-worker utilization, rpc-wait vs compute, "
                        "stragglers, clock-alignment error")
    p.add_argument("--routed", action="store_true",
                   help="fleet view of a merged router trace: rpc.route "
                        "<-> shard rpc.query correlation, per-replica "
                        "tracks, clock-alignment error")
    p.add_argument("--bundle", action="store_true",
                   help="render a flight-recorder postmortem bundle "
                        "(bundle.json, fleet_bundle.json, or a bundle "
                        "directory) instead of a trace")
    p.add_argument("--exemplars", action="store_true",
                   help="render a tail-sampled exemplar file "
                        "(exemplars.jsonl or the --debug-dir holding "
                        "one): retention breakdown + slowest kept span "
                        "trees (ISSUE 19)")
    args = p.parse_args(argv)
    if args.exemplars:
        try:
            recs = load_exemplar_file(args.trace_file)
        except TraceLoadError as e:
            print(f"trace_report: error: {e}", file=sys.stderr)
            return 1
        print(exemplar_report(recs, top=args.top))
        return 0
    if args.bundle:
        try:
            doc = load_bundle(args.trace_file)
        except TraceLoadError as e:
            print(f"trace_report: error: {e}", file=sys.stderr)
            return 1
        print(bundle_report(doc))
        return 0
    try:
        events = load_all(args.trace_file)
    except TraceLoadError as e:
        print(f"trace_report: error: {e}", file=sys.stderr)
        return 1
    if args.cluster:
        print(cluster_report(events, top=args.top))
        return 0
    if args.routed:
        print(routed_report(events))
        return 0
    spans = sorted((e for e in events if e.get("ph") == "X"),
                   key=lambda e: e["ts"])
    if not spans:
        print("no span events in trace", file=sys.stderr)
        return 1
    print(report(spans, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
