"""Decompose the 1e9 single-segment pallas run: host prep vs device kernel
vs postlude vs coordinator overhead. Run on the real chip."""

from __future__ import annotations

import os
import sys
import time

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def t(fn, reps=3):
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        best = min(best, time.perf_counter() - t0)
    return best, out


def main():
    import jax

    jax.devices()  # initialize the platform plugin before any jit

    from sieve.kernels.pallas_mark import (
        _build_call, _build_call_jit, mark_pallas, prepare_pallas,
    )
    from sieve.seed import seed_primes

    n = int(float(sys.argv[1])) if len(sys.argv) > 1 else 10**9
    lo, hi = 2, n + 1
    import math

    seeds = seed_primes(math.isqrt(n))
    print(f"n={n:.0e} seeds={seeds.size}")

    dt, ps = t(lambda: prepare_pallas("odds", lo, hi, seeds))
    print(f"prepare_pallas (host):      {dt*1e3:9.1f} ms")

    # incremental chain prepare (what the streamed mesh/local paths pay per
    # segment after init) with its per-phase split; re-preparing the same
    # segment is a zero-delta advance, i.e. exactly the steady-state cost
    from sieve.kernels.pallas_mark import PallasChain

    chain = PallasChain("odds", seeds, ps.Wpad)
    chain.prepare(lo, hi)  # init: one-time from-scratch residue derivation
    base = dict(chain.phase_seconds)
    reps = 3
    dt, _ = t(lambda: chain.prepare(lo, hi), reps=reps)
    phases = " ".join(
        f"{k}={(v - base.get(k, 0.0)) / reps * 1e3:.1f}"
        for k, v in chain.phase_seconds.items()
    )
    print(f"chain prepare (host, incr): {dt*1e3:9.1f} ms   "
          f"avg phases ms: {phases}")
    SB = ps.B[0].shape[1]
    SC = ps.C[0].shape[1]
    ND = ps.D[0].shape[0] if ps.D[3].any() else 0
    print(f"  Wpad={ps.Wpad} SB={SB} SC={SC} ND={ND} "
          f"CC={ps.corr_idx.shape[1]}")

    # kernel only (no postlude), warm
    call = _build_call(ps.Wpad, SB, SC, ND, interpret=False)
    args = tuple(ps.A) + tuple(ps.B) + tuple(ps.C) + tuple(ps.D)
    jcall = jax.jit(lambda *a: call(*a))
    jcall(*args).block_until_ready()
    dt, _ = t(lambda: jcall(*args).block_until_ready())
    print(f"pallas kernel only (device):{dt*1e3:9.1f} ms")

    # kernel + postlude (the full mark_pallas jit), warm
    FC = ps.flat_idx.shape[1] if ps.flat_mask.any() else 0
    full = _build_call_jit(ps.Wpad, 1, SB, SC, ND, FC, False)
    fargs = (np.int32(ps.nbits), np.uint32(ps.pair_mask), args,
             ps.corr_idx[0], ps.corr_mask[0],
             ps.flat_idx[0, :FC], ps.flat_mask[0, :FC])
    jax.block_until_ready(full(*fargs))
    dt, _ = t(lambda: jax.block_until_ready(full(*fargs)))
    print(f"kernel + postlude (device): {dt*1e3:9.1f} ms")

    # fused mark+reduce (single pallas_call, no postlude round trip);
    # in-kernel reduce cost ~= fused minus the kernel-only mark pass
    from sieve.kernels.pallas_mark import _build_fused_jit, fused_args

    CC = ps.corr_idx.shape[1]
    FCf = ps.flat_idx.shape[1]
    fused = _build_fused_jit(ps.Wpad, SB, SC, ND, CC, FCf, 1, False, False)
    fa = fused_args(ps)
    jax.block_until_ready(fused(*fa))
    dt_mark, _ = t(lambda: jcall(*args).block_until_ready())
    dt, _ = t(lambda: jax.block_until_ready(fused(*fa)))
    print(f"fused mark+reduce (device): {dt*1e3:9.1f} ms   "
          f"(mark {dt_mark*1e3:.1f} ms, in-kernel reduce "
          f"~{max(0.0, dt - dt_mark)*1e3:.1f} ms)")

    # whole mark_pallas incl. host->device transfers of specs
    dt, _ = t(lambda: mark_pallas(ps, 1, False))
    print(f"mark_pallas end-to-end:     {dt*1e3:9.1f} ms")

    # full run_local
    from sieve.config import SieveConfig
    from sieve.coordinator import run_local

    cfg = SieveConfig(n=n, backend="tpu-pallas", packing="odds",
                      n_segments=1, twins=False, quiet=True)
    run_local(cfg)
    dt, res = t(lambda: run_local(cfg))
    print(f"run_local end-to-end:       {dt*1e3:9.1f} ms   pi={res.pi}")


if __name__ == "__main__":
    main()
