"""Failover smoke: the replicated query plane under a live writer, a
replica SIGKILL, injected refresh corruption, and a graceful drain —
every reply bit-exact or typed, never silent, never wrong (ISSUE 8
acceptance; tier-1 via tests/test_service.py).

Builds a fully-sieved source dir, seeds a *serving* dir with only its
first segments, and drives the replication story end to end:

1. seed — sieve n into ``src``; copy the first 3 of 8 segments into the
   serving ledger a concurrent writer will keep extending.
2. replicas — two ``python -m sieve serve`` subprocesses on the serving
   dir (``--refresh-s 0.15 --allow-chaos``), plus a :class:`ReplicaSet`
   client over both.
3. live load — a writer thread records the remaining segments every
   ~0.25 s while mixed queries run against the set; mid-load replica 1
   gets a ``replica_down`` window and then a real SIGKILL. Every reply
   must be oracle-exact or a typed overloaded / deadline_exceeded /
   degraded / draining error; a health monitor on replica 2 asserts
   ``covered_hi`` is nondecreasing and strictly grew (>= 1 refresh).
4. refresh corruption — ``svc_refresh_corrupt`` directives on replica
   2's next refresh attempts: ``refresh_failed`` rises, serving
   continues on the previous snapshot, and a later poll recovers.
5. drain — with a cold query in flight, replica 2 gets SIGTERM: the
   in-flight reply comes back exact, a queued follow-up on an open
   connection gets a typed ``draining``, and the process exits 0 with
   its "drained" line reporting a clean drain (zero dropped in-flight).

Exit status: 0 on full parity, 1 on any violation (with a FAIL line).

Usage: python tools/failover_smoke.py [--n N] [--keep DIR]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np

ORACLE_HI = 400_000
ALLOWED_ERRORS = {"overloaded", "deadline_exceeded", "degraded", "draining"}


def fail(msg: str) -> None:
    print(f"FAIL: {msg}", flush=True)
    sys.exit(1)


def expect(desc: str, got, want) -> None:
    if got != want:
        fail(f"{desc}: got {got!r}, want {want!r}")


class Replica:
    """One ``sieve serve`` subprocess + its stdout line collector."""

    def __init__(self, args: list[str], env: dict):
        self.proc = subprocess.Popen(
            args, env=env, cwd=REPO, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        )
        head = self.proc.stdout.readline()
        try:
            self.serving = json.loads(head)
        except ValueError:
            self.proc.kill()
            raise RuntimeError(f"serve did not announce itself: {head!r}")
        self.addr = self.serving["addr"]
        self.lines: list[str] = []
        self._reader = threading.Thread(target=self._drain, daemon=True)
        self._reader.start()

    def _drain(self) -> None:
        for line in self.proc.stdout:
            self.lines.append(line)

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=10)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--n", type=int, default=200_000)
    p.add_argument("--keep", default=None,
                   help="use (and keep) this work dir instead of a temp dir")
    args = p.parse_args(argv)
    if args.n > ORACLE_HI // 2:
        fail(f"--n must stay at or below {ORACLE_HI // 2} (oracle headroom)")

    from sieve.checkpoint import Ledger
    from sieve.config import SieveConfig
    from sieve.coordinator import run_local
    from sieve.seed import seed_primes
    from sieve.service import ReplicaSet, ServiceClient

    P = seed_primes(ORACLE_HI)

    def o_pi(x: int) -> int:
        return int(np.searchsorted(P, x, side="right"))

    def o_count(lo: int, hi: int) -> int:
        return int(np.searchsorted(P, hi, side="left")
                   - np.searchsorted(P, lo, side="left"))

    def o_primes(lo: int, hi: int) -> list[int]:
        return [int(v) for v in P[(P >= lo) & (P < hi)]]

    def o_pairs(lo: int, hi: int, gap: int) -> int:
        w = P[(P >= lo) & (P < hi)]
        if w.size < 2:
            return 0
        idx = np.searchsorted(w, w + gap)
        ok = idx < w.size
        return int(np.count_nonzero(w[idx[ok]] == w[ok] + gap))

    workdir = args.keep or tempfile.mkdtemp(prefix="failover_smoke.")
    src = os.path.join(workdir, "src")
    serve_dir = os.path.join(workdir, "serving")
    reps: list[Replica] = []
    try:
        # --- phase 1: sieve src fully, seed the serving ledger -----------
        src_cfg = SieveConfig(
            n=args.n, backend="cpu-numpy", packing="wheel30",
            n_segments=8, quiet=True, checkpoint_dir=src,
        )
        print(f"phase 1: sieving source dir (n={args.n}, 8 segments)",
              flush=True)
        run_local(src_cfg)
        segs = sorted(
            Ledger.open_readonly(src_cfg).completed().values(),
            key=lambda r: r.lo,
        )
        serve_cfg = dataclasses.replace(src_cfg, checkpoint_dir=serve_dir)
        wled = Ledger.open(serve_cfg)  # the live writer's ledger
        for r in segs[:3]:
            wled.record(r)
        print(f"phase 1 OK: serving ledger seeded with 3/8 segments "
              f"(covered_hi={segs[2].hi})", flush=True)

        # --- phase 2: two replicas + a ReplicaSet over both --------------
        env = dict(
            os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
            SIEVE_SVC_COLD_DELAY_S="0.3",
        )
        serve_args = [
            sys.executable, "-m", "sieve", "serve",
            "--addr", "127.0.0.1:0", "--n", str(args.n),
            "--packing", "wheel30", "--segments", "8",
            "--checkpoint-dir", serve_dir, "--refresh-s", "0.15",
            "--drain-s", "10", "--allow-chaos", "--deadline-s", "10",
            "--quiet",
        ]
        reps = [Replica(serve_args, env), Replica(serve_args, env)]
        expect("replica 0 startup segments", reps[0].serving["segments"], 3)
        rs = ReplicaSet([r.addr for r in reps], timeout_s=30, rounds=4)
        expect("replica set sanity pi", rs.pi(50_000), o_pi(50_000))
        print(f"phase 2 OK: replicas at {reps[0].addr} / {reps[1].addr}",
              flush=True)

        # --- phase 3: live writer + chaos + SIGKILL under client load ----
        mon = ServiceClient(reps[1].addr, timeout_s=30)
        seen_hi: list[int] = []
        mon_stop = threading.Event()
        mon_errs: list[str] = []

        def monitor() -> None:
            while not mon_stop.is_set():
                h = mon.health()
                if seen_hi and h["covered_hi"] < seen_hi[-1]:
                    mon_errs.append(
                        f"covered_hi regressed {seen_hi[-1]} -> "
                        f"{h['covered_hi']}"
                    )
                seen_hi.append(h["covered_hi"])
                time.sleep(0.05)

        def writer() -> None:
            for r in segs[3:]:
                time.sleep(0.25)
                wled.record(r)

        tmon = threading.Thread(target=monitor, daemon=True)
        twr = threading.Thread(target=writer, daemon=True)
        tmon.start()
        twr.start()

        full_hi = segs[-1].hi
        wrong = 0
        typed: dict[str, int] = {}
        n_exact = 0
        plan = [
            ("pi", {"x": 50_000}, o_pi(50_000)),
            ("pi", {"x": args.n - 1}, o_pi(args.n - 1)),
            ("count", {"lo": 10_000, "hi": 60_000}, o_count(10_000, 60_000)),
            ("nth_prime", {"k": 1000}, int(P[999])),
            ("primes", {"lo": 70_000, "hi": 70_200}, o_primes(70_000, 70_200)),
            ("pi", {"x": 120_000}, o_pi(120_000)),
            ("count", {"lo": 2, "hi": 30_000, "kind": "twins"},
             o_pairs(2, 30_000, 2)),
        ]
        for i in range(36):
            op, params, want = plan[i % len(plan)]
            if i == 8:
                # a dead replica from the client's side: replica 1 drops
                # every connection without replying for 1 s. The directive
                # keys on the replica's request sequence number, which
                # tracks its admitted-request counter; a small spread
                # absorbs any drift between the two.
                with ServiceClient(reps[0].addr, timeout_s=10) as c:
                    seq = c.stats()["requests"]
                    c.inject_chaos(",".join(
                        f"replica_down:any@s{seq + j}:1.0"
                        for j in range(1, 7)
                    ))
            if i == 18:
                reps[0].kill()  # SIGKILL mid-load: hard replica loss
            rep = rs.query(op, **params)
            if rep.get("ok"):
                if want is not None and rep["value"] != want:
                    wrong += 1
                    print(f"WRONG: {op}{params} -> {rep['value']}, "
                          f"want {want}", flush=True)
                else:
                    n_exact += 1
            else:
                kind = rep.get("error")
                typed[kind] = typed.get(kind, 0) + 1
                if kind not in ALLOWED_ERRORS:
                    fail(f"untyped/unexpected error under load: {rep!r}")
            time.sleep(0.06)
        twr.join(timeout=30)
        if twr.is_alive():
            fail("writer thread hung")

        # replica 2 must catch up to the fully-written ledger
        deadline = time.monotonic() + 10
        while mon.health()["covered_hi"] < full_hi:
            if time.monotonic() > deadline:
                fail(f"replica 2 never refreshed to covered_hi={full_hi} "
                     f"(at {mon.health()['covered_hi']})")
            time.sleep(0.1)
        mon_stop.set()
        tmon.join(timeout=5)
        if mon_errs:
            fail(f"monitor: {mon_errs[0]}")
        h = mon.health()
        if h["refreshes"] < 1:
            fail(f"replica 2 reported {h['refreshes']} refreshes, want >= 1")
        if not any(b > a for a, b in zip(seen_hi, seen_hi[1:])):
            fail("monitor never observed covered_hi strictly increase")
        if wrong:
            fail(f"{wrong} WRONG values under load")
        if n_exact < 20:
            fail(f"only {n_exact}/36 exact replies under load")
        if rs.failovers < 1:
            fail("ReplicaSet never failed over despite a killed replica")
        # post-refresh exactness on the survivor: the full range is hot now
        expect("post-refresh pi(n-1)", rs.pi(args.n - 1), o_pi(args.n - 1))
        print(f"phase 3 OK: {n_exact} exact, typed {typed}, "
              f"failovers={rs.failovers}, covered_hi {seen_hi[0]} -> "
              f"{seen_hi[-1]}, refreshes={h['refreshes']}", flush=True)

        # --- phase 4: injected refresh corruption is a skipped refresh ---
        s0 = mon.stats()
        att = s0["refresh_attempts"]
        mon.inject_chaos(f"svc_refresh_corrupt:any@s{att + 1}")
        wled.record(segs[-1])  # idempotent rewrite: moves the fingerprint
        deadline = time.monotonic() + 10
        while mon.stats()["refresh_failed"] <= s0["refresh_failed"]:
            if time.monotonic() > deadline:
                fail("svc_refresh_corrupt never produced a failed refresh")
            time.sleep(0.1)
        expect("covered_hi unchanged across corrupt refresh",
               mon.health()["covered_hi"], full_hi)
        expect("still exact across corrupt refresh", mon.pi(90_000),
               o_pi(90_000))
        # the follower retries and recovers once the directive is consumed
        deadline = time.monotonic() + 10
        while mon.stats()["refresh_attempts"] <= att + 1:
            if time.monotonic() > deadline:
                fail("follower never retried after the corrupt refresh")
            time.sleep(0.1)
        print(f"phase 4 OK: corrupt refresh skipped "
              f"(refresh_failed={mon.stats()['refresh_failed']}), serving "
              f"uninterrupted", flush=True)

        # --- phase 5: graceful drain loses zero in-flight answers --------
        inflight_cli = ServiceClient(reps[1].addr, timeout_s=30)
        queued_cli = ServiceClient(reps[1].addr, timeout_s=30)
        want_cold = o_pi(390_000)
        box: dict = {}

        def fire() -> None:
            try:
                box["reply"] = inflight_cli.query("pi", x=390_000)
            except BaseException as e:  # noqa: BLE001 — checked below
                box["err"] = e

        t = threading.Thread(target=fire)
        t.start()
        time.sleep(0.15)  # inside the 0.3 s simulated cold latency
        reps[1].proc.send_signal(signal.SIGTERM)
        time.sleep(0.1)
        shed = queued_cli.query("pi", x=1000)
        if shed.get("ok") or shed.get("error") != "draining":
            fail(f"query after SIGTERM: want typed draining, got {shed!r}")
        t.join(timeout=30)
        if t.is_alive():
            fail("in-flight query hung across drain")
        if "err" in box:
            fail(f"in-flight query dropped during drain: {box['err']!r}")
        expect("in-flight reply across drain", box["reply"].get("value"),
               want_cold)
        rc = reps[1].proc.wait(timeout=30)
        expect("drained replica exit code", rc, 0)
        drained = [json.loads(l) for l in reps[1].lines
                   if '"drained"' in l]
        if not drained or not drained[0].get("clean"):
            fail(f"no clean 'drained' line from replica 2: {reps[1].lines}")
        inflight_cli.close()
        queued_cli.close()
        mon.close()
        rs.close()
        print("phase 5 OK: in-flight exact, new query typed draining, "
              "exit 0, drain clean", flush=True)
        print("FAILOVER_SMOKE_OK", flush=True)
        return 0
    finally:
        for r in reps:
            r.kill()
        if args.keep is None:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
