#!/usr/bin/env python3
"""Env-var discipline checker (ISSUE 15 satellite).

Two rules, both over ``sieve/``, ``tools/`` and ``bench.py``:

1. **No raw reads.** Every ``SIEVE_*`` environment variable must be
   read through the validators in :mod:`sieve.env` (``env_int`` /
   ``env_float`` / ``env_str`` / ``env_flag`` / ``env_items``), which
   produce actionable errors on malformed values instead of a bare
   ``ValueError`` deep in a worker thread. A direct
   ``os.environ.get("SIEVE_...")`` / ``os.environ["SIEVE_..."]`` /
   ``os.getenv("SIEVE_...")`` read anywhere outside ``sieve/env.py``
   is a failure. *Writes* (``setdefault``, subscript stores, building
   a child-process environment dict) are fine — the rule is about
   parsing config, not exporting it.

2. **Documented.** Every ``SIEVE_*`` name that appears as a complete
   string literal in the code (read sites, prefix constants, child-env
   keys) must appear in ``README.md``. Names ending in ``_`` are
   prefixes (``SIEVE_SVC_SLO_MS_<OP>``) and match as substrings too.

Both rules are absolute, not ratcheted: the repo is clean today and a
regression is a one-line fix (route the read through ``sieve.env`` /
add the variable to the README table).
"""

from __future__ import annotations

import ast
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
README = os.path.join(REPO, "README.md")
SCAN = ("sieve", "tools")
EXTRA_FILES = ("bench.py",)
# the validator module itself is the one place raw reads are legal
RAW_READ_EXEMPT = {os.path.join("sieve", "env.py")}

_NAME_RE = re.compile(r"^SIEVE_[A-Z0-9_]+$")


def _py_files() -> list[str]:
    out = []
    for top in SCAN:
        for dirpath, dirnames, filenames in os.walk(os.path.join(REPO, top)):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    for fn in EXTRA_FILES:
        p = os.path.join(REPO, fn)
        if os.path.exists(p):
            out.append(p)
    return sorted(out)


def _is_environ(node: ast.expr) -> bool:
    """True for ``os.environ`` (or a bare ``environ`` import)."""
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        return True
    return isinstance(node, ast.Name) and node.id == "environ"


def _sieve_literal(node: ast.expr) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if _NAME_RE.match(node.value):
            return node.value
    return None


class _Scanner(ast.NodeVisitor):
    def __init__(self, rel: str) -> None:
        self.rel = rel
        self.raw_reads: list[tuple[int, str]] = []
        self.names: set[str] = set()

    def visit_Constant(self, node: ast.Constant) -> None:
        name = _sieve_literal(node)
        if name:
            self.names.add(name)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        # os.environ.get("SIEVE_...") / os.environ.setdefault(...)
        if (isinstance(func, ast.Attribute) and _is_environ(func.value)
                and func.attr == "get" and node.args):
            name = _sieve_literal(node.args[0])
            if name:
                self.raw_reads.append((node.lineno, name))
        # os.getenv("SIEVE_...")
        if (isinstance(func, ast.Attribute) and func.attr == "getenv"
                and node.args):
            name = _sieve_literal(node.args[0])
            if name:
                self.raw_reads.append((node.lineno, name))
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # os.environ["SIEVE_..."] in Load context is a raw read;
        # a subscript *store* is exporting to children and is fine
        if _is_environ(node.value) and isinstance(node.ctx, ast.Load):
            name = _sieve_literal(node.slice)
            if name:
                self.raw_reads.append((node.lineno, name))
        self.generic_visit(node)


def scan() -> tuple[list[str], set[str]]:
    """Returns (raw-read problem strings, all SIEVE_* literal names)."""
    problems: list[str] = []
    names: set[str] = set()
    for path in _py_files():
        rel = os.path.relpath(path, REPO)
        try:
            tree = ast.parse(open(path, encoding="utf-8").read())
        except SyntaxError as exc:
            problems.append(f"{rel}: unparseable: {exc}")
            continue
        sc = _Scanner(rel)
        sc.visit(tree)
        names |= sc.names
        if rel in RAW_READ_EXEMPT:
            continue
        for lineno, name in sc.raw_reads:
            problems.append(
                f"{rel}:{lineno}: raw read of {name} — go through "
                "sieve.env (env_int/env_float/env_str/env_flag/env_items)"
            )
    return problems, names


def undocumented(names: set[str]) -> list[str]:
    text = open(README, encoding="utf-8").read()
    missing = []
    for name in sorted(names):
        # trailing-underscore names are prefixes; both forms match as a
        # plain substring (the README writes SIEVE_SVC_SLO_MS_<OP>)
        if name not in text:
            missing.append(name)
    return missing


def main(argv: list[str] | None = None) -> int:
    problems, names = scan()
    for name in undocumented(names):
        problems.append(f"README.md: {name} is not documented")
    for p in problems:
        print(f"check_env_vars: {p}", file=sys.stderr)
    if problems:
        print(f"check_env_vars: FAILED ({len(problems)} problem(s))",
              file=sys.stderr)
        return 1
    print(f"check_env_vars: ok ({len(names)} SIEVE_* vars, all "
          "validated + documented)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
