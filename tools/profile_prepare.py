"""Per-phase breakdown of host spec preparation.

Times the incremental chains (PallasChain / TieredChain) over a run of
contiguous depth-regime segments and splits steady-state cost into the
phases the chains instrument — residue math (the O(1) modular advance),
grouping/compaction (A/B/C/D assembly or tier-2 table build), flat
crossing enumeration, corrections merge — plus the mesh-style stacking
cost that follows prepare on the round critical path. From-scratch
prepare of the same segments is timed for comparison, so the tool answers
"where does the remaining host-prepare time go, and what did incremental
reuse buy".

Host-only (pure numpy): runs anywhere, no device or jit involved.

usage: python tools/profile_prepare.py [span] [segments] [packing]
    span      per-segment value span        (default 1e8)
    segments  timed steady-state segments   (default 8)
    packing   plain | odds | wheel30        (default odds)
"""

from __future__ import annotations

import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

DEPTH_HI = 10**12 + 1  # seed set = the full 78,498 primes below 10^6


def _phase_table(title: str, phases: dict[str, float], total: float,
                 nseg: int) -> None:
    print(f"{title}  ({total / nseg * 1e3:.1f} ms/segment)")
    other = total - sum(phases.values())
    for k, v in [*phases.items(), ("other", other)]:
        pct = 100.0 * v / total if total > 0 else 0.0
        print(f"    {k:<14} {v / nseg * 1e3:9.2f} ms/seg  {pct:5.1f}%")


def main() -> int:
    span = int(float(sys.argv[1])) if len(sys.argv) > 1 else 10**8
    nseg = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    packing = sys.argv[3] if len(sys.argv) > 3 else "odds"

    from sieve.bitset import get_layout
    from sieve.kernels.jax_mark import SPEC_BLOCK, TIER1_MAX, WORD_BUCKET
    from sieve.kernels.pallas_mark import (
        TILE_WORDS,
        PallasChain,
        prepare_pallas,
    )
    from sieve.kernels.specs import TieredChain, prepare_tiered
    from sieve.seed import seed_primes

    lo0 = 10**12 - (nseg + 1) * span
    seeds = seed_primes(math.isqrt(DEPTH_HI - 1))
    layout = get_layout(packing)
    bounds = [(lo0 + i * span, lo0 + (i + 1) * span) for i in range(nseg + 1)]
    W = max(-(-layout.nbits(lo, hi) // 32) for lo, hi in bounds)
    wpad = -(-(W + 1) // TILE_WORDS) * TILE_WORDS
    print(f"packing={packing} span={span:.0e} segments={nseg} "
          f"seeds={seeds.size} wpad={wpad}")

    # ---- pallas chain: steady state after the init segment ----
    chain = PallasChain(packing, seeds, wpad)
    t0 = time.perf_counter()
    chain.prepare(*bounds[0])
    init_s = time.perf_counter() - t0
    base = dict(chain.phase_seconds)
    t0 = time.perf_counter()
    preps = [chain.prepare(lo, hi) for lo, hi in bounds[1:]]
    incr_s = time.perf_counter() - t0
    phases = {
        k: v - base.get(k, 0.0) for k, v in chain.phase_seconds.items()
    }
    print(f"\nPallasChain init segment (from-scratch residues): "
          f"{init_s * 1e3:.1f} ms")
    _phase_table("PallasChain steady-state prepare", phases, incr_s, nseg)

    # mesh-style stacking of the round batch (what follows prepare on the
    # round critical path; pad_pallas is a no-op here — same chain, same
    # shapes)
    t0 = time.perf_counter()
    [np.stack([p.A[i] for p in preps]) for i in range(6)]
    [np.stack([p.B[i] for p in preps]) for i in range(6)]
    [np.stack([p.C[i] for p in preps]) for i in range(4)]
    [np.stack([p.D[i] for p in preps]) for i in range(4)]
    np.stack([p.corr_idx for p in preps])
    np.stack([p.corr_mask for p in preps])
    np.stack([p.flat_idx for p in preps])
    np.stack([p.flat_mask for p in preps])
    stack_s = time.perf_counter() - t0
    print(f"    mesh stacking  {stack_s / nseg * 1e3:9.2f} ms/seg")

    t0 = time.perf_counter()
    for lo, hi in bounds[1:3]:
        prepare_pallas(packing, lo, hi, seeds, wpad=wpad)
    scratch = (time.perf_counter() - t0) / 2
    print(f"from-scratch prepare_pallas: {scratch * 1e3:.1f} ms/segment "
          f"-> chain speedup {scratch / (incr_s / nseg):.2f}x")

    # ---- word-kernel tiered chain ----
    tchain = TieredChain(packing, seeds, TIER1_MAX, SPEC_BLOCK, WORD_BUCKET)
    tchain.prepare(*bounds[0])
    tbase = dict(tchain.phase_seconds)
    t0 = time.perf_counter()
    for lo, hi in bounds[1:]:
        tchain.prepare(lo, hi)
    tincr_s = time.perf_counter() - t0
    tphases = {
        k: v - tbase.get(k, 0.0) for k, v in tchain.phase_seconds.items()
    }
    print()
    _phase_table("TieredChain steady-state prepare", tphases, tincr_s, nseg)

    t0 = time.perf_counter()
    for lo, hi in bounds[1:3]:
        prepare_tiered(packing, lo, hi, seeds, tier1_max=TIER1_MAX,
                       spec_block=SPEC_BLOCK, word_bucket=WORD_BUCKET)
    tscratch = (time.perf_counter() - t0) / 2
    print(f"from-scratch prepare_tiered: {tscratch * 1e3:.1f} ms/segment "
          f"-> chain speedup {tscratch / (tincr_s / nseg):.2f}x")
    return 0


if __name__ == "__main__":
    sys.exit(main())
