"""Live fleet telemetry: poll a sieve router + every shard replica into
one refreshing terminal table (ISSUE 12).

Each poll asks the router for health (which names every shard replica
address), stats, and the new ``metrics`` wire op, then asks each replica
for the same three. The rendered table shows, per replica: lane queue
depths, shed/demotion rates, LRU and cold-cache hit rates, cold dispatch
rate, the cold-backend class column (``mesh/DxF`` = D mesh devices at
last-drain chunk fanout F, or the loop backend name — ISSUE 18),
the segment-store column (hit ratio / demotions, plus a ``T<n>``
torn-entry marker — ISSUE 17), covered_hi, the worst per-op SLO
burn, and the ``hot frame`` column (ISSUE 20: the top self-time frame
from a cached low-rate pull of each process's continuous profiler,
refreshed at most every 10s) — plus a router header
with request rate, totals-cache hit rate, telemetry merge/gap counters,
and fabric coverage contiguity. Rates are deltas between consecutive
polls; the first frame shows totals only.

Percentiles with zero observations render as ``-`` — never a fake 0.

``--json`` (ISSUE 13) takes one poll and prints the raw snapshot as a
single JSON document for scripts and cron probes — no table, no screen
clear — exiting 1 if the router is unreachable or any shard/replica row
would render DOWN or UNREACHABLE.

Usage:
    python tools/fleet_top.py 127.0.0.1:7733 [--interval 2.0] [--once]
    python tools/fleet_top.py 127.0.0.1:7733 --json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sieve.profile import self_times  # noqa: E402
from sieve.service.client import ClientPool, ServiceClient  # noqa: E402
from tools.trace_report import _sparkline  # noqa: E402

_CLEAR = "\x1b[2J\x1b[H"

# snapshots of trend history per sparkline cell (--observe-dir)
_TREND_DEPTH = 30

# the hot-frame column (ISSUE 20) refreshes its per-endpoint profile
# pull at most this often — a watch session must not turn the profiler
# into a per-poll tax
_PROF_REFRESH_S = 10.0


def _hot_frame(profile: dict | None) -> str:
    """Top SELF-time frame of one endpoint's profile document, or ``-``
    (profiler disabled, or no samples yet)."""
    if not profile:
        return "-"
    merged = {r["stack"]: {"count": r["count"], "role": r.get("role")}
              for r in profile.get("stacks") or []}
    rows = self_times(merged, 1)
    if not rows:
        return "-"
    return f"{rows[0]['frame']} {rows[0]['share']:.0%}"


def _hot_frame_cached(cli: "ServiceClient", addr: str,
                      prof_cache: dict | None) -> str:
    """The endpoint's hot frame from a cached low-rate profile pull.

    A failed pull (old server, ``svc_prof_gap`` drop) degrades to the
    cached cell — never the row's health."""
    if prof_cache is None:
        try:
            return _hot_frame(cli.profile())
        except Exception:  # noqa: BLE001
            return "-"
    now = time.time()
    ent = prof_cache.get(addr)
    if ent is not None and now - ent[0] < _PROF_REFRESH_S:
        return ent[1]
    cell = ent[1] if ent is not None else "-"
    try:
        cell = _hot_frame(cli.profile())
    except Exception:  # noqa: BLE001 — keep the stale cell
        pass
    prof_cache[addr] = (now, cell)
    return cell


def _poll(addr: str, timeout_s: float,
          pool: ClientPool | None = None,
          prof_cache: dict | None = None) -> dict[str, Any]:
    """health + stats + metrics of one endpoint, or a named error.

    With a ``pool`` (ISSUE 14) the endpoint's pipelined connection is
    reused across refresh cycles — one TCP connect per target for the
    whole watch session instead of one per poll — and a transport
    failure invalidates just that entry so the next cycle reconnects
    (counted in ``pool.reconnects``)."""
    try:
        if pool is not None:
            cli = pool.get(addr)
            return {
                "addr": addr,
                "health": cli.health(),
                "stats": cli.stats(),
                "metrics": cli.metrics(),
                "hot_frame": _hot_frame_cached(cli, addr, prof_cache),
                "error": None,
            }
        with ServiceClient(addr, timeout_s=timeout_s) as cli:
            return {
                "addr": addr,
                "health": cli.health(),
                "stats": cli.stats(),
                "metrics": cli.metrics(),
                "hot_frame": _hot_frame_cached(cli, addr, prof_cache),
                "error": None,
            }
    except Exception as e:  # noqa: BLE001 — a dead replica is a table row
        if pool is not None:
            pool.invalidate(addr)
        return {"addr": addr, "health": None, "stats": None,
                "metrics": None, "hot_frame": "-",
                "error": f"{type(e).__name__}: {e}"}


def fleet_snapshot(router_addr: str, timeout_s: float = 5.0,
                   pool: ClientPool | None = None,
                   prof_cache: dict | None = None) -> dict:
    """One poll of the whole fleet (pure data; rendering is separate).

    Returns ``{"ts": epoch_s, "router": {...}, "shards": [...]}`` where
    each shard entry carries the router's view (range, status) plus a
    polled row per replica address. Pass one :class:`ClientPool` across
    consecutive calls to reuse every endpoint's connection, and one
    ``prof_cache`` dict to rate-limit the hot-frame profile pulls
    (ISSUE 20) to one per endpoint per ``_PROF_REFRESH_S``."""
    router = _poll(router_addr, timeout_s, pool, prof_cache)
    shards: list[dict[str, Any]] = []
    h = router["health"]
    if h is not None:
        for ent in h.get("shards", []):
            shards.append({
                "shard": ent.get("shard"),
                "lo": ent.get("lo"),
                "hi": ent.get("hi"),
                "status": ent.get("status"),
                "replicas": [
                    _poll(a, timeout_s, pool, prof_cache)
                    for a in ent.get("addrs", [])
                ],
            })
    return {"ts": time.time(), "router": router, "shards": shards}


def fleet_ok(snap: dict) -> bool:
    """True when every row of a snapshot would render healthy.

    False if the router itself is unreachable, any shard's router-side
    status is down/unreachable, or any replica poll came back without a
    health block (the table's DOWN rows)."""
    if snap["router"]["health"] is None:
        return False
    for sh in snap["shards"]:
        if str(sh.get("status", "")).lower() in ("down", "unreachable"):
            return False
        for rep in sh["replicas"]:
            if rep["health"] is None:
                return False
    return True


def ring_trends(observe_dir: str,
                depth: int = _TREND_DEPTH) -> dict[str, dict[str, list]]:
    """Per-endpoint signal series from the observer's snapshot ring
    (ISSUE 19): ``{addr: {signal: [newest depth values...]}}``. The
    observer daemon persists the ring; this reader tolerates a racing
    appender (torn tails skip) and an absent file (empty trends)."""
    from sieve.service.observe import RING_FILE, read_ring

    out: dict[str, dict[str, list]] = {}
    path = os.path.join(observe_dir, RING_FILE)
    for snap in read_ring(path)[-depth:]:
        for tgt in snap.get("targets", []):
            sig = tgt.get("signals")
            if not isinstance(sig, dict):
                continue  # gap row: no fabricated point
            series = out.setdefault(tgt.get("addr", "?"), {})
            for name, val in sig.items():
                series.setdefault(name, []).append(val)
    return out


def _trend_cell(trends: dict | None, addr: str, signal: str) -> str:
    if not trends or addr not in trends:
        return "-"
    return _sparkline(trends[addr].get(signal) or [])


def _rate(cur: dict | None, prev: dict | None, key: str,
          dt: float | None) -> str:
    """Per-second delta between polls, or the running total on frame 1."""
    if cur is None:
        return "-"
    v = cur.get(key)
    if v is None:
        return "-"
    if prev is None or dt is None or dt <= 0 or prev.get(key) is None:
        return str(v)
    return f"{max(0, v - prev[key]) / dt:.1f}/s"


def _ratio(num: int | None, den: int | None) -> str:
    if not den:
        return "-"
    return f"{100.0 * (num or 0) / den:.0f}%"


def _worst_burn(stats: dict | None) -> str:
    """Worst per-op SLO burn from a replica's ``slo`` stats block; ``-``
    when no SLOs are set or no op has observations yet."""
    if not stats:
        return "-"
    slo = stats.get("slo") or {}
    burns = [v.get("burn") for v in slo.values()
             if isinstance(v, dict) and v.get("burn") is not None]
    if not burns:
        return "-"
    worst = max(burns)
    return f"{worst:.2f}x" + ("!" if worst > 1.0 else "")


def _store_cell(stats: dict | None) -> str:
    """``hit%/demotions`` from the nested segment-store stats block
    (ISSUE 17), or ``-`` when the replica runs without a store."""
    if not stats:
        return "-"
    st = stats.get("store")
    if not st:
        return "-"
    hits = st.get("hits") or 0
    misses = st.get("misses") or 0
    hit = _ratio(hits, hits + misses)
    cell = f"{hit}/{st.get('demotions', 0)}"
    torn = st.get("torn") or 0
    return cell + (f" T{torn}" if torn else "")


def _cold_cell(stats: dict | None) -> str:
    """Cold-plane worker class (ISSUE 18): ``mesh/DxF`` for a mesh
    replica (D devices, F chunks in the last drain fanout), the plain
    backend name otherwise, ``-`` for pre-mesh servers."""
    if not stats:
        return "-"
    backend = stats.get("cold_backend")
    if not backend:
        return "-"
    if str(backend).startswith("mesh") and stats.get("mesh_devices"):
        return (f"mesh/{stats.get('mesh_devices')}"
                f"x{stats.get('mesh_fanout', 0)}")
    return str(backend)


def _prev_stats(prev: dict | None, shard: int | None,
                addr: str) -> dict | None:
    if prev is None:
        return None
    for sh in prev.get("shards", []):
        if sh.get("shard") != shard:
            continue
        for rep in sh.get("replicas", []):
            if rep.get("addr") == addr:
                return rep.get("stats")
    return None


def render(snap: dict, prev: dict | None = None,
           trends: dict | None = None) -> str:
    """One text frame from a :func:`fleet_snapshot` (pure function).

    ``trends`` (from :func:`ring_trends`, the ``--observe-dir`` mode)
    appends per-endpoint hot-qps and shed-rate sparkline columns fed
    from the observer daemon's snapshot ring."""
    lines: list[str] = []
    dt = (snap["ts"] - prev["ts"]) if prev else None
    r = snap["router"]
    rh, rs, rm = r["health"], r["stats"], r["metrics"]
    if rh is None:
        return f"router {r['addr']}: UNREACHABLE ({r['error']})"
    covered = rh.get("covered_hi") or 0
    hi = rh.get("range_hi") or 0
    contiguous = covered >= hi
    tot_hit = (rm.get("router.totals_hit") or {}).get("value", 0)
    tot_miss = (rm.get("router.totals_miss") or {}).get("value", 0)
    lines.append(
        f"router {r['addr']}  status={rh.get('status')}  "
        f"shards={rh.get('shard_count')}  "
        f"range=[{rh.get('range_lo')}, {hi})  "
        f"covered_hi={covered} "
        f"({'contiguous' if contiguous else 'GAP'})"
    )
    prs = prev["router"]["stats"] if prev and prev["router"]["stats"] else None
    lines.append(
        f"  requests={_rate(rs, prs, 'requests', dt)}  "
        f"scattered={_rate(rs, prs, 'scattered', dt)}  "
        f"totals-cache hit={_ratio(tot_hit, tot_hit + tot_miss)}  "
        f"telemetry merged={rs.get('telemetry_merged', 0)} "
        f"gaps={rs.get('telemetry_gaps', 0)}  "
        f"failovers={rs.get('failovers', 0)}  "
        f"hot={r.get('hot_frame', '-')}"
    )
    lines.append("")
    trend_hdr = (f" {'hot trend':>{_TREND_DEPTH}} "
                 f"{'shed trend':>{_TREND_DEPTH}}"
                 if trends is not None else "")
    lines.append(
        f"  {'replica':<22} {'st':<4} {'hot':>4} {'cold':>4} "
        f"{'shed':>8} {'demote':>8} {'lru':>5} {'ccache':>6} "
        f"{'colddisp':>9} {'cbackend':>10} {'store':>12} "
        f"{'covered_hi':>11} {'slo burn':>9} {'hot frame':<28}"
        + trend_hdr
    )
    for sh in snap["shards"]:
        for rep in sh["replicas"]:
            name = f"s{sh['shard']} {rep['addr']}"
            if rep["health"] is None:
                lines.append(f"  {name:<22} DOWN ({rep['error']})")
                continue
            h, st = rep["health"], rep["stats"]
            ps = _prev_stats(prev, sh["shard"], rep["addr"])
            shed = (st.get("shed", 0) + st.get("lane_shed_hot", 0)
                    + st.get("lane_shed_cold", 0))
            shed_r = _rate({"shed_all": shed},
                           {"shed_all": ((ps.get("shed", 0)
                                          + ps.get("lane_shed_hot", 0)
                                          + ps.get("lane_shed_cold", 0))
                                         if ps else None)},
                           "shed_all", dt)
            lru = _ratio(st.get("lru_hits"),
                         (st.get("lru_hits") or 0)
                         + (st.get("cold_computes") or 0))
            ccache = _ratio(st.get("cold_cache_hits"),
                            (st.get("cold_cache_hits") or 0)
                            + (st.get("cold_dispatches") or 0))
            trend_cells = (
                f" {_trend_cell(trends, rep['addr'], 'hot_qps'):>{_TREND_DEPTH}}"
                f" {_trend_cell(trends, rep['addr'], 'shed_rate'):>{_TREND_DEPTH}}"
                if trends is not None else ""
            )
            lines.append(
                f"  {name:<22} {str(h.get('status', '?'))[:4]:<4} "
                f"{h.get('queue_depth_hot', 0):>4} "
                f"{h.get('queue_depth_cold', 0):>4} "
                f"{shed_r:>8} {_rate(st, ps, 'demoted', dt):>8} "
                f"{lru:>5} {ccache:>6} "
                f"{_rate(st, ps, 'cold_dispatches', dt):>9} "
                f"{_cold_cell(st):>10} "
                f"{_store_cell(st):>12} "
                f"{h.get('covered_hi', 0):>11} {_worst_burn(st):>9} "
                f"{rep.get('hot_frame', '-'):<28}"
                + trend_cells
            )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="live fleet table over a sieve router and its shard "
                    "replicas (health + stats + the metrics wire op)"
    )
    p.add_argument("router_addr", help="router host:port")
    p.add_argument("--interval", type=float, default=2.0,
                   help="seconds between polls (default 2)")
    p.add_argument("--timeout", type=float, default=5.0,
                   help="per-endpoint RPC timeout")
    p.add_argument("--once", action="store_true",
                   help="render one frame and exit (no screen clear)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="one poll, raw snapshot as a single JSON document; "
                        "exit 1 if any row is DOWN or UNREACHABLE")
    p.add_argument("--observe-dir", default=None,
                   help="a `python -m sieve observe` --observe-dir: adds "
                        "per-replica hot-qps / shed-rate sparkline "
                        "columns fed from the observer's snapshot ring "
                        "(ISSUE 19)")
    args = p.parse_args(argv)
    if args.as_json:
        snap = fleet_snapshot(args.router_addr, timeout_s=args.timeout)
        print(json.dumps(snap))
        return 0 if fleet_ok(snap) else 1
    prev: dict | None = None
    # one pipelined client per endpoint, reused across refresh cycles
    # (ISSUE 14): a watch session costs one connect per target, not one
    # per poll; reconnects are counted and shown in the header
    # the hot-frame cells refresh from a rate-limited profile pull
    # (ISSUE 20): one per endpoint per _PROF_REFRESH_S, not per poll
    prof_cache: dict = {}
    with ClientPool(timeout_s=args.timeout) as pool:
        try:
            while True:
                snap = fleet_snapshot(args.router_addr,
                                      timeout_s=args.timeout, pool=pool,
                                      prof_cache=prof_cache)
                trends = (ring_trends(args.observe_dir)
                          if args.observe_dir else None)
                frame = render(snap, prev, trends=trends)
                if args.once:
                    print(frame)
                    return 0 if snap["router"]["health"] is not None else 1
                print(f"{_CLEAR}{time.strftime('%H:%M:%S')}  "
                      f"(every {args.interval:g}s, ctrl-C to quit)  "
                      f"[conns={pool.connects} "
                      f"reconnects={pool.reconnects}]")
                print(frame, flush=True)
                prev = snap
                time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
