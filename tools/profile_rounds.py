"""Time the mesh round path (workers=1) on the real chip: warm, then
measure. A thin wrapper over the span tracer — the warm run is traced
and summarized with tools/trace_report.py (pass --trace-out FILE to
also keep the Perfetto-loadable file).

Usage: python tools/profile_rounds.py [n] [rounds] [--twins]
           [--trace-out FILE]"""

from __future__ import annotations

import os
import sys
import time

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    argv = sys.argv[1:]
    trace_out = None
    if "--trace-out" in argv:
        i = argv.index("--trace-out")
        trace_out = argv[i + 1]
        del argv[i : i + 2]
    args = [a for a in argv if not a.startswith("--")]
    n = int(float(args[0])) if args else 10**10
    rounds = int(args[1]) if len(args) > 1 else 8
    twins = "--twins" in argv

    from sieve.config import SieveConfig
    from sieve.parallel.mesh import run_mesh

    from sieve import trace
    from tools.trace_report import load_events, report

    cfg = SieveConfig(n=n, backend="tpu-pallas", packing="odds", workers=1,
                      rounds=rounds, twins=twins, quiet=True)
    t0 = time.perf_counter()
    res = run_mesh(cfg)
    cold = time.perf_counter() - t0
    trace.enable()  # capture spans for the warm (steady-state) run only
    t0 = time.perf_counter()
    res = run_mesh(cfg)
    warm = time.perf_counter() - t0
    trace.disable()
    print(f"n={n:.0e} rounds={rounds} twins={twins} pi={res.pi} "
          f"twin={res.twin_pairs}")
    print(f"cold={cold:.2f}s warm={warm:.2f}s "
          f"({(n - 1) / warm:.3e} values/s warm)")

    if trace_out is not None:
        trace.save(trace_out)
        print(f"trace written to {trace_out}")
    import io

    buf = io.StringIO()
    trace.save(buf)
    buf.seek(0)
    print()
    print(report(load_events(buf)))


if __name__ == "__main__":
    main()
