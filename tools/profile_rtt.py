"""Separate axon-tunnel round-trip latency from true device compute:
time k back-to-back kernel dispatches with ONE final scalar readback.
Slope over k = real per-dispatch device time; intercept = RTT + fixed."""

from __future__ import annotations

import os
import sys
import time

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import math

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from sieve.kernels.pallas_mark import _build_call, _postlude, prepare_pallas
    from sieve.seed import seed_primes

    n = int(float(sys.argv[1])) if len(sys.argv) > 1 else 10**9
    lo, hi = 2, n + 1
    seeds = seed_primes(math.isqrt(n))

    # RTT floor: trivial scalar jit round trip
    f = jax.jit(lambda x: x + 1)
    int(f(np.int32(1)))
    t0 = time.perf_counter()
    for _ in range(5):
        v = int(f(np.int32(1)))
    rtt = (time.perf_counter() - t0) / 5
    print(f"scalar jit round-trip:  {rtt*1e3:8.1f} ms")

    ps = prepare_pallas("odds", lo, hi, seeds)
    SB, SC = ps.B[0].shape[1], ps.C[0].shape[1]
    ND = ps.D[0].shape[0] if ps.D[3].any() else 0
    call = _build_call(ps.Wpad, SB, SC, ND, interpret=False)
    args = tuple(ps.A) + tuple(ps.B) + tuple(ps.C) + tuple(ps.D)

    def chain(k):
        @jax.jit
        def run(*a):
            acc = jnp.uint32(0)
            for _ in range(k):
                w = call(*a)
                c, tw, fw, lw = _postlude(
                    w, np.int32(ps.nbits), np.uint32(ps.pair_mask),
                    ps.corr_idx[0], ps.corr_mask[0], 1,
                    ps.flat_idx[0], ps.flat_mask[0])
                acc = acc + c.astype(jnp.uint32)
            return acc

        return run

    for k in (1, 2, 4, 8):
        r = chain(k)
        int(r(*args))  # compile + warm
        t0 = time.perf_counter()
        v = int(r(*args))
        dt = time.perf_counter() - t0
        print(f"k={k}: total {dt*1e3:8.1f} ms   ({dt/k*1e3:8.1f} ms/dispatch)")


def main2():
    """Device-resident args variant: isolates transfer cost (--args)."""
    import jax
    import math
    import jax.numpy as jnp

    from sieve.kernels.pallas_mark import _build_call_jit, prepare_pallas
    from sieve.seed import seed_primes

    n = int(float(sys.argv[1])) if len(sys.argv) > 1 else 10**9
    seeds = seed_primes(math.isqrt(n))
    ps = prepare_pallas("odds", 2, n + 1, seeds)
    SB, SC = ps.B[0].shape[1], ps.C[0].shape[1]
    ND = ps.D[0].shape[0] if ps.D[3].any() else 0
    FC = ps.flat_idx.shape[1] if ps.flat_mask.any() else 0
    full = _build_call_jit(ps.Wpad, 1, SB, SC, ND, FC, False)
    host_args = (np.int32(ps.nbits), np.uint32(ps.pair_mask),
                 tuple(ps.A) + tuple(ps.B) + tuple(ps.C) + tuple(ps.D),
                 ps.corr_idx[0], ps.corr_mask[0],
                 ps.flat_idx[0, :FC], ps.flat_mask[0, :FC])
    dev_args = jax.device_put(host_args)
    jax.block_until_ready(dev_args)
    for label, args in (("host args", host_args), ("device args", dev_args)):
        np.asarray(full(*args))  # warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(full(*args))
            best = min(best, time.perf_counter() - t0)
        print(f"{label}: {best*1e3:8.1f} ms end-to-end")
    t0 = time.perf_counter()
    dev_args2 = jax.device_put(host_args)
    jax.block_until_ready(dev_args2)
    print(f"device_put of args: {(time.perf_counter()-t0)*1e3:8.1f} ms")


if __name__ == "__main__":
    main2() if "--args" in sys.argv else main()
