"""Static check: every emitted event kind must be in EVENT_SCHEMA
(ISSUE 13 satellite — keeps the schema honest as the event surface
grows; tier-1 via tests/test_debug.py).

Greps every ``<logger>.event("kind", ...)`` call and every literal
record passed to ``validate_record({... "event": "kind" ...})`` across
sieve/, tools/, and bench.py (tests excluded — they exercise bogus
kinds on purpose), then fails with a ``path:line: kind`` line per kind
that :data:`sieve.metrics.EVENT_SCHEMA` does not document. Console
head lines like cli.py's ``{"event": "serving"}`` are not metrics
records and are deliberately not matched.

Usage: python tools/check_event_schema.py [ROOT]
"""

from __future__ import annotations

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sieve.metrics import EVENT_SCHEMA  # noqa: E402

# .event( may put the kind string on the next line — allow whitespace
_EVENT_CALL = re.compile(r"\.event\(\s*['\"]([a-z0-9_]+)['\"]")
_VALIDATE_LITERAL = re.compile(
    r"validate_record\(\s*\{[^}]*['\"]event['\"]\s*:\s*['\"]([a-z0-9_]+)['\"]",
    re.S,
)


def _py_files(root: str) -> list[str]:
    out: list[str] = []
    for sub in ("sieve", "tools"):
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            out.extend(os.path.join(dirpath, f) for f in filenames
                       if f.endswith(".py")
                       and f != "check_event_schema.py")  # own docstring
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        out.append(bench)
    return sorted(out)


def missing_kinds(root: str) -> list[tuple[str, int, str]]:
    """Every ``(path, line, kind)`` emission site whose kind is absent
    from EVENT_SCHEMA. Empty list means the schema is honest."""
    bad: list[tuple[str, int, str]] = []
    for path in _py_files(root):
        with open(path, encoding="utf-8") as f:
            text = f.read()
        for pat in (_EVENT_CALL, _VALIDATE_LITERAL):
            for m in pat.finditer(text):
                kind = m.group(1)
                if kind not in EVENT_SCHEMA:
                    line = text.count("\n", 0, m.start()) + 1
                    rel = os.path.relpath(path, root)
                    bad.append((rel, line, kind))
    return bad


def main(argv: list[str] | None = None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    root = args[0] if args else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    bad = missing_kinds(root)
    for rel, line, kind in bad:
        print(f"{rel}:{line}: event kind '{kind}' missing from "
              "EVENT_SCHEMA (sieve/metrics.py)", file=sys.stderr)
    if bad:
        return 1
    print(f"check_event_schema: ok ({len(EVENT_SCHEMA)} kinds documented)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
