"""Shard smoke: the range-sharded router fabric under an oracle sweep
across the shard edge, a mid-load shard-replica SIGKILL, a whole-shard
outage, recovery, and an injected ``svc_shard_down`` window — every
reply bit-exact or typed, never silent, never wrong (ISSUE 11
acceptance; tier-1 via tests/test_router.py).

Builds a fully-sieved source dir, splits its segments into two shard
ledgers at a segment boundary E, and drives the fabric end to end:

1. seed — sieve n into ``src``; segments below E go to the shard-0
   ledger, the rest to shard 1's.
2. fabric — 2 shards x 2 replicas (four ``python -m sieve serve``
   subprocesses; shard 1's run with ``--range-lo E``) fronted by one
   ``python -m sieve route`` subprocess. An oracle sweep crosses the
   edge: pi / count / twins / cousins straddling E, nth_prime across
   the cumulative boundary, primes concatenated across shards,
   is_prime on both sides. Scatter-gather must cache both full-shard
   totals.
3. failover — SIGKILL one shard-1 replica mid-load; every reply stays
   oracle-exact and the router's per-shard ReplicaSet records >= 1
   failover.
4. outage — SIGKILL the surviving shard-1 replica: a query needing
   shard 1 gets a typed ``unavailable`` NAMING the shard (index +
   range), while shard-0-only queries — and pi(n), answerable from
   cached immutable totals — stay exact.
5. recovery — restart one shard-1 replica on its old address; the
   router fails back over and edge queries go exact again.
6. chaos — a wire-injected ``svc_shard_down`` window holds shard 0
   unreachable: shard-1 point queries stay exact, shard-0 queries get
   the typed ``unavailable``, and after the window expires the fabric
   recovers with zero restarts.

Exit status: 0 on full parity (final line ``SHARD_SMOKE_OK``), 1 on
any violation (with a FAIL line).

Usage: python tools/shard_smoke.py [--n N] [--keep DIR]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np

ORACLE_HI = 400_000


def fail(msg: str) -> None:
    print(f"FAIL: {msg}", flush=True)
    sys.exit(1)


def expect(desc: str, got, want) -> None:
    if got != want:
        fail(f"{desc}: got {got!r}, want {want!r}")


class Proc:
    """One ``sieve serve``/``sieve route`` subprocess + line collector."""

    def __init__(self, args: list[str], env: dict):
        self.args = args
        self.proc = subprocess.Popen(
            args, env=env, cwd=REPO, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        )
        head = self.proc.stdout.readline()
        try:
            self.serving = json.loads(head)
        except ValueError:
            self.proc.kill()
            raise RuntimeError(f"process did not announce itself: {head!r}")
        self.addr = self.serving["addr"]
        self.lines: list[str] = []
        self._reader = threading.Thread(target=self._drain, daemon=True)
        self._reader.start()

    def _drain(self) -> None:
        for line in self.proc.stdout:
            self.lines.append(line)

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=10)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--n", type=int, default=200_000)
    p.add_argument("--keep", default=None,
                   help="use (and keep) this work dir instead of a temp dir")
    args = p.parse_args(argv)
    if args.n > ORACLE_HI // 2:
        fail(f"--n must stay at or below {ORACLE_HI // 2} (oracle headroom)")

    from sieve.checkpoint import Ledger
    from sieve.config import SieveConfig
    from sieve.coordinator import run_local
    from sieve.seed import seed_primes
    from sieve.service import ServiceClient

    P = seed_primes(ORACLE_HI)

    def o_pi(x: int) -> int:
        return int(np.searchsorted(P, x, side="right"))

    def o_count(lo: int, hi: int) -> int:
        return int(np.searchsorted(P, hi, side="left")
                   - np.searchsorted(P, lo, side="left"))

    def o_primes(lo: int, hi: int) -> list[int]:
        return [int(v) for v in P[(P >= lo) & (P < hi)]]

    def o_pairs(lo: int, hi: int, gap: int) -> int:
        w = P[(P >= lo) & (P < hi)]
        if w.size < 2:
            return 0
        idx = np.searchsorted(w, w + gap)
        ok = idx < w.size
        return int(np.count_nonzero(w[idx[ok]] == w[ok] + gap))

    workdir = args.keep or tempfile.mkdtemp(prefix="shard_smoke.")
    src = os.path.join(workdir, "src")
    procs: list[Proc] = []
    try:
        # --- phase 1: sieve src, split segments into two shard ledgers ---
        src_cfg = SieveConfig(
            n=args.n, backend="cpu-numpy", packing="wheel30",
            n_segments=8, quiet=True, checkpoint_dir=src,
        )
        print(f"phase 1: sieving source dir (n={args.n}, 8 segments)",
              flush=True)
        run_local(src_cfg)
        segs = sorted(
            Ledger.open_readonly(src_cfg).completed().values(),
            key=lambda r: r.lo,
        )
        E = segs[4].lo  # the shard edge, on a segment boundary
        dirs = [os.path.join(workdir, d) for d in ("shard0", "shard1")]
        for d, part in zip(dirs, (segs[:4], segs[4:])):
            led = Ledger.open(dataclasses.replace(src_cfg, checkpoint_dir=d))
            for r in part:
                led.record(r)
        print(f"phase 1 OK: shard ledgers split at edge E={E}", flush=True)

        # --- phase 2: 2 shards x 2 replicas + router, oracle edge sweep --
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)

        def serve_args(d: str, range_lo: int, addr: str) -> list[str]:
            a = [
                sys.executable, "-m", "sieve", "serve",
                "--addr", addr, "--n", str(args.n),
                "--packing", "wheel30", "--segments", "8",
                "--checkpoint-dir", d, "--deadline-s", "10",
                "--drain-s", "10", "--quiet",
            ]
            if range_lo > 2:
                a += ["--range-lo", str(range_lo)]
            return a

        s0 = [Proc(serve_args(dirs[0], 2, "127.0.0.1:0"), env)
              for _ in range(2)]
        s1 = [Proc(serve_args(dirs[1], E, "127.0.0.1:0"), env)
              for _ in range(2)]
        procs.extend(s0 + s1)
        router = Proc([
            sys.executable, "-m", "sieve", "route",
            "--addr", "127.0.0.1:0", "--allow-chaos", "--quiet",
            "--deadline-s", "10", "--timeout-s", "15",
            "--shard", f"2:{E}={s0[0].addr},{s0[1].addr}",
            "--shard", f"{E}:{args.n + 1}={s1[0].addr},{s1[1].addr}",
        ], env)
        procs.append(router)
        expect("router announce event", router.serving["event"], "routing")
        cli = ServiceClient(router.addr, timeout_s=30)

        k_mid = o_pi(E - 1) + 50  # an nth_prime served by shard 1
        sweep = [
            ("pi", {"x": args.n}, o_pi(args.n)),
            ("pi", {"x": E - 1}, o_pi(E - 1)),
            ("pi", {"x": E}, o_pi(E)),
            ("pi", {"x": E + 1}, o_pi(E + 1)),
            ("count", {"lo": E - 500, "hi": E + 500}, o_count(E - 500, E + 500)),
            ("count", {"lo": E - 500, "hi": E + 500, "kind": "twins"},
             o_pairs(E - 500, E + 500, 2)),
            ("count", {"lo": E - 500, "hi": E + 500, "kind": "cousins"},
             o_pairs(E - 500, E + 500, 4)),
            ("count", {"lo": 2, "hi": args.n + 1, "kind": "twins"},
             o_pairs(2, args.n + 1, 2)),
            ("nth_prime", {"k": k_mid}, int(P[k_mid - 1])),
            ("primes", {"lo": E - 100, "hi": E + 100}, o_primes(E - 100, E + 100)),
            ("is_prime", {"x": int(P[o_pi(E)])}, True),
            ("is_prime", {"x": int(P[o_pi(E)]) + 1}, False),
        ]
        for op, params, want in sweep:
            rep = cli.query(op, **params)
            if not rep.get("ok"):
                fail(f"edge sweep {op}{params}: typed {rep!r}")
            expect(f"edge sweep {op}{params}", rep["value"], want)
        st = cli.stats()
        expect("full-shard totals cached", st["totals_cached"], 2)
        print(f"phase 2 OK: {len(sweep)} edge queries exact "
              f"(router at {router.addr}, totals_cached=2)", flush=True)

        # --- phase 3: SIGKILL one shard-1 replica mid-load ---------------
        plan = [
            ("count", {"lo": E + 10, "hi": E + 2000}, o_count(E + 10, E + 2000)),
            ("is_prime", {"x": int(P[k_mid])}, True),
            ("count", {"lo": E - 300, "hi": E + 300, "kind": "twins"},
             o_pairs(E - 300, E + 300, 2)),
            ("primes", {"lo": E - 50, "hi": E + 50}, o_primes(E - 50, E + 50)),
        ]
        for i in range(12):
            if i == 3:
                s1[0].kill()  # hard shard-replica loss mid-load
            op, params, want = plan[i % len(plan)]
            rep = cli.query(op, **params)
            if not rep.get("ok"):
                fail(f"failover load {op}{params}: typed {rep!r}")
            expect(f"failover load {op}{params}", rep["value"], want)
        st = cli.stats()
        if st["failovers"] < 1:
            fail(f"router never failed over (stats {st['failovers']})")
        print(f"phase 3 OK: 12 exact under replica loss, "
              f"failovers={st['failovers']}", flush=True)

        # --- phase 4: whole shard down -> typed unavailable, named ------
        s1[1].kill()
        rep = cli.query("count", lo=E + 10, hi=E + 2000)
        expect("whole-shard-down error kind", rep.get("error"), "unavailable")
        expect("unavailable names the shard", rep.get("shard"), 1)
        expect("unavailable carries the range", rep.get("shard_range"),
               [E, args.n + 1])
        if "shard 1" not in rep.get("detail", ""):
            fail(f"unavailable detail does not name shard 1: {rep!r}")
        # shard-0-only queries keep answering exact through the outage,
        # and pi(n) still composes from the cached immutable totals
        expect("shard-0 query during outage", cli.query(
            "count", lo=10_000, hi=60_000)["value"], o_count(10_000, 60_000))
        expect("pi(n) from cached totals during outage",
               cli.query("pi", x=args.n)["value"], o_pi(args.n))
        print("phase 4 OK: whole-shard outage typed unavailable "
              "(shard 1 named), shard 0 + cached totals exact", flush=True)

        # --- phase 5: restart a shard-1 replica on its old addr ---------
        s1[0] = Proc(serve_args(dirs[1], E, s1[0].addr), env)
        procs.append(s1[0])
        deadline = time.monotonic() + 20
        while True:
            rep = cli.query("count", lo=E + 10, hi=E + 2000)
            if rep.get("ok"):
                expect("post-recovery count", rep["value"],
                       o_count(E + 10, E + 2000))
                break
            if time.monotonic() > deadline:
                fail(f"router never recovered after restart: {rep!r}")
            time.sleep(0.2)
        print("phase 5 OK: restarted replica picked back up, edge exact",
              flush=True)

        # --- phase 6: injected svc_shard_down window on shard 0 ---------
        seq = cli.stats()["requests"]
        cli.inject_chaos(",".join(
            f"svc_shard_down:0@s{seq + j}:1.5" for j in range(1, 3)
        ))
        # the next request draws the directive and opens the window; a
        # shard-1 point query is untouched by a shard-0 outage. pi(E-1)
        # would STILL answer (cached immutable total), so the probe is a
        # partial-range count that must contact shard 0.
        expect("shard-1 point query inside window", cli.query(
            "is_prime", x=int(P[k_mid]))["value"], True)
        rep = cli.query("count", lo=10_000, hi=60_000)  # needs shard 0
        expect("windowed shard-0 error kind", rep.get("error"), "unavailable")
        expect("windowed shard named", rep.get("shard"), 0)
        st = cli.stats()
        if st["shard_down_windows"] < 1:
            fail(f"no shard_down window recorded: {st!r}")
        time.sleep(1.6)  # let the window expire
        deadline = time.monotonic() + 10
        while True:
            rep = cli.query("count", lo=10_000, hi=60_000)
            if rep.get("ok"):
                expect("post-window count", rep["value"],
                       o_count(10_000, 60_000))
                break
            if time.monotonic() > deadline:
                fail(f"fabric never recovered after the window: {rep!r}")
            time.sleep(0.2)
        cli.close()
        print("phase 6 OK: svc_shard_down window typed + scoped, fabric "
              "recovered with zero restarts", flush=True)
        print("SHARD_SMOKE_OK", flush=True)
        return 0
    finally:
        for pr in procs:
            pr.kill()
        if args.keep is None:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
