"""Benchmark: the shallow AND depth regimes of the pallas sieve, plus the
host-prepare pipeline, the fused-reduction bandwidth model, and the
query-service latency profile.

Prints EIGHT JSON lines {"metric", "value", "unit", "vs_baseline"}:

1. pi(1e9), odds packing, tpu-pallas backend — the shallow regime.
   Baseline: BASELINE.md's measured CPU floor — pi(1e9) segmented numpy
   in 7.5 s single process == 1.33e8 values/s.
2. Warm values/s on ONE 10^9-span odds segment at lo = 10^12 - 10^9 with
   the full 78,498-seed set (ND=609 group-D blocks) — the regime the
   north star (pi(10^12) < 60 s) actually lives in, where the rate used
   to collapse 11.5x below the shallow number. Baseline: the 4.06e8
   values/s/chip probe measured on v5e (VERDICT.md round 5). Emitted on
   TPU only (interpret mode would take hours); force with
   SIEVE_BENCH_DEPTH=1.
3. Host-prepare throughput of the incremental chain (specs off the
   critical path): steady-state PallasChain values/s over depth-regime
   segments with the full seed set. vs_baseline = speedup over
   from-scratch prepare_pallas of the same segments. The line also
   carries overlap_efficiency / device_idle_frac measured from a real
   streamed mesh round loop. Host-only: emitted on any platform.
4. Fused-reduction segment HBM traffic as a fraction of the split
   (kernel + XLA postlude) path, from the byte-exact spec/bitset sizes
   of a real prepared depth-regime-shaped segment: the split path
   writes the packed bitset to HBM and re-reads every word in the
   postlude (2 full bitset passes); the fused path ships only the
   (1, 8) accumulator plus the per-tile cursor tables. Gated on a
   bit-exact fused-vs-split parity check of that same segment.
   vs_baseline = 0.55 / ratio, so >= 1 means the "one bitset pass
   eliminated" target of ISSUE 3 is met. Host-only: emitted anywhere.
5. Query-service latency (ISSUE 9): p50/p95 ms per op measured from the
   ``rpc.query`` trace spans of a mixed hot/cold workload against an
   in-process SieveService over a freshly sieved checkpoint dir. The
   headline value is the overall p95 in ms (unit ``ms_p95`` — gated
   UPWARD by tools/bench_compare.py: a >10% p95 increase between rounds
   fails); vs_baseline = 50 ms budget / p95, so >= 1 is within budget.
   Host-only: emitted anywhere.
6. Hot-lane p95 under a cold flood (ISSUE 10): the same ``ms_p95``
   gate applied to hot-lane ``rpc.query`` spans while 20 threads
   saturate the cold plane — the lane-isolation guarantee as a number.
   vs_baseline = 50 ms budget / p95. Host-only: emitted anywhere.
7. Router fabric latency (ISSUE 11): overall p95 in ms from the
   ``rpc.route`` spans of a mixed workload against a two-shard
   in-process fabric (each shard its own ledger slice, shard 1 serving
   with ``range_lo``) — point routes, scatter-gather prefix counts
   (cached full-shard totals + boundary queries), windowed counts, and
   twin windows straddling the shard edge (the splice path). Unit
   ``ms_p95`` (same upward gate); vs_baseline = 50 ms budget / p95.
   Host-only: emitted anywhere.
8. Fleet-tracing overhead (ISSUE 12): client-observed p95 of the line-5
   mixed workload with the full trace plane on (span capture, bounded
   ship ring, reply piggybacks) divided by the same workload's p95 with
   it off. Unit ``overhead_ratio`` — gated ABSOLUTELY by
   tools/bench_compare.py: a value > 1.05 (tracing costs more than 5%
   of p95) fails regardless of the previous round. vs_baseline =
   1.05 / ratio, so >= 1 is within budget. Host-only: emitted anywhere.

9. Mesh cold-drain throughput (ISSUE 18): values/s through one drain
   slice of equal-span cold chunks on the mesh backend — ONE
   shard_map/jit SPMD launch spanning every device — via
   tools/mesh_cold_smoke.py in a subprocess (8-way virtual CPU mesh).
   Unit ``cold_throughput`` (drop-gated by tools/bench_compare.py);
   vs_baseline = speedup over the loop backend's K sequential
   markings, so >= 1 means the one-launch drain wins. Host-only:
   emitted anywhere.

Exact parity is asserted before any number is printed — the depth line
against a cpu-numpy run of the same segment: a fast wrong sieve scores
zero. The service line asserts every reply exact against the index
oracle before timing counts.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time

# persistent XLA compile cache: cuts repeat bench runs from minutes to seconds
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

N = 10**9
PI_N = 50_847_534  # BASELINE.md oracle (computed, 2026-07-29)
BASELINE_VALUES_PER_SEC = (N - 1) / 7.5  # BASELINE.md CPU floor

DEPTH_SPAN = 10**9
DEPTH_LO = 10**12 - DEPTH_SPAN
DEPTH_HI = 10**12 + 1  # seed set = seed_primes(10^6) = 78,498 primes
# VERDICT.md round-5 probe: 2.45 s warm per 10^9-value segment on one v5e
DEPTH_BASELINE_VALUES_PER_SEC = 4.06e8


def shallow_metric() -> None:
    from sieve.config import SieveConfig
    from sieve.coordinator import run_local

    cfg = SieveConfig(
        n=N, backend="tpu-pallas", packing="odds", n_segments=1, twins=False,
        quiet=True,
    )
    # warmup: compile every shape bucket once (first TPU compile is slow and
    # is not the thing being measured)
    warm = run_local(cfg)
    assert warm.pi == PI_N, f"warmup parity failure: {warm.pi} != {PI_N}"

    t0 = time.perf_counter()
    res = run_local(cfg)
    elapsed = time.perf_counter() - t0
    assert res.pi == PI_N, f"parity failure: {res.pi} != {PI_N}"

    values_per_sec = (N - 1) / elapsed
    print(
        json.dumps(
            {
                "metric": "sieve_throughput_pi_1e9_odds_pallas",
                "value": round(values_per_sec, 1),
                "unit": "values/s/chip",
                "vs_baseline": round(values_per_sec / BASELINE_VALUES_PER_SEC, 3),
            }
        )
    )


def depth_metric() -> None:
    import jax

    from sieve import env

    if jax.devices()[0].platform != "tpu" and not env.env_str(
        "SIEVE_BENCH_DEPTH"
    ):
        print(
            "depth metric skipped: no TPU (interpret mode would take hours; "
            "force with SIEVE_BENCH_DEPTH=1)",
            file=sys.stderr,
        )
        return

    from sieve.backends.cpu_numpy import CpuNumpyWorker
    from sieve.backends.tpu_pallas import PallasWorker
    from sieve.config import SieveConfig
    from sieve.seed import seed_primes

    lo, hi = DEPTH_LO, DEPTH_HI
    cfg = SieveConfig(
        n=10**12, backend="tpu-pallas", packing="odds", twins=True, quiet=True
    )
    seeds = seed_primes(math.isqrt(hi - 1))
    worker = PallasWorker(cfg)
    cold = worker.process_segment(lo, hi, seeds)  # compile + warm caches

    # exact parity against the segment-level numpy reference (~10 s host):
    # no oracle table covers pi(10^12) - pi(10^12 - 10^9)
    ref = CpuNumpyWorker(cfg).process_segment(lo, hi, seeds)
    got = (cold.count, cold.twin_count, cold.first_word, cold.last_word)
    want = (ref.count, ref.twin_count, ref.first_word, ref.last_word)
    assert got == want, f"depth parity failure: {got} != {want}"

    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        res = worker.process_segment(lo, hi, seeds)
        best = min(best, time.perf_counter() - t0)
        assert res.count == ref.count, "depth rerun parity failure"

    values_per_sec = (hi - lo) / best
    print(
        json.dumps(
            {
                "metric": "sieve_throughput_depth_1e12_odds_pallas",
                "value": round(values_per_sec, 1),
                "unit": "values/s/chip",
                "vs_baseline": round(
                    values_per_sec / DEPTH_BASELINE_VALUES_PER_SEC, 3
                ),
            }
        )
    )


def host_prepare_metric() -> None:
    """Host-only line (runs on any platform): steady-state incremental
    chain-prepare throughput at depth-regime stride density, vs from-scratch
    prepare_pallas of the same segments, plus overlap efficiency / device
    idle fraction from a real streamed mesh round loop."""
    from sieve.bitset import get_layout
    from sieve.config import SieveConfig
    from sieve.kernels.pallas_mark import (
        TILE_WORDS,
        PallasChain,
        prepare_pallas,
    )
    from sieve.parallel.mesh import run_mesh
    from sieve.seed import seed_primes

    span = 10**8
    k = 9  # segment 0 initializes the chain; 1..k-1 are timed steady state
    seeds = seed_primes(math.isqrt(DEPTH_HI - 1))  # full 78,498-seed set
    layout = get_layout("odds")
    bounds = [
        (DEPTH_LO + i * span, DEPTH_LO + (i + 1) * span) for i in range(k)
    ]
    W = max(-(-layout.nbits(lo, hi) // 32) for lo, hi in bounds)
    wpad = -(-(W + 1) // TILE_WORDS) * TILE_WORDS

    chain = PallasChain("odds", seeds, wpad)
    chain.prepare(*bounds[0])  # one-time from-scratch residue derivation
    t0 = time.perf_counter()
    for lo, hi in bounds[1:]:
        chain.prepare(lo, hi)
    incr_per_seg = (time.perf_counter() - t0) / (k - 1)

    t0 = time.perf_counter()
    for lo, hi in bounds[1:3]:
        prepare_pallas("odds", lo, hi, seeds, wpad=wpad)
    scratch_per_seg = (time.perf_counter() - t0) / 2

    # overlap efficiency: a real streamed run (background prepare threads
    # feeding the round loop) on whatever device this host has
    cfg = SieveConfig(
        n=30_000_000, backend="jax", packing="odds", workers=1, rounds=6,
        twins=False, quiet=True,
    )
    res = run_mesh(cfg)
    assert res.pi == 1_857_859, f"mesh parity failure: {res.pi}"
    ph = res.host_phases or {}

    print(
        json.dumps(
            {
                "metric": "host_prepare_throughput_odds_pallas",
                "value": round(span / incr_per_seg, 1),
                "unit": "values/s",
                # speedup of incremental chain prepare over from-scratch
                "vs_baseline": round(scratch_per_seg / incr_per_seg, 3),
                "overlap_efficiency": ph.get("overlap_efficiency"),
                "device_idle_frac": ph.get("device_idle_frac"),
            }
        )
    )


def fused_reduction_metric() -> None:
    """Fused vs split reduction: parity gate + segment HBM traffic ratio.

    Traffic is modeled from the actual prepared arrays of one segment
    (spec streams are read by BOTH paths; only the bitset round trip
    differs): split = specs + bitset write + bitset re-read; fused =
    specs + per-tile cursors + the 32-byte accumulator. The parity gate
    runs both kernels on the device (interpret mode off-TPU) and refuses
    to print a number if they disagree — a fast wrong reduction scores
    zero."""
    import jax

    from sieve.kernels.jax_mark import TWIN_ADJ
    from sieve.kernels.pallas_mark import (
        mark_pallas_fused,
        mark_pallas_split,
        prepare_pallas,
    )
    from sieve.seed import seed_primes

    lo, hi = 2_000_003, 24_000_001
    seeds = seed_primes(math.isqrt(hi - 1))
    ps = prepare_pallas("odds", lo, hi, seeds)
    interpret = jax.devices()[0].platform != "tpu"
    fused = mark_pallas_fused(ps, TWIN_ADJ, interpret)
    split = mark_pallas_split(ps, TWIN_ADJ, interpret)
    assert fused == split, f"fused parity failure: {fused} != {split}"

    spec_bytes = sum(
        a.nbytes
        for a in (
            *ps.A, *ps.B, *ps.C, *ps.D,
            ps.corr_idx, ps.corr_mask, ps.flat_idx, ps.flat_mask,
        )
    )
    from sieve.kernels.pallas_mark import TILE_WORDS

    bitset_bytes = ps.Wpad * 4
    cursor_bytes = 2 * (ps.Wpad // TILE_WORDS + 1) * 4
    split_bytes = spec_bytes + 2 * bitset_bytes
    fused_bytes = spec_bytes + cursor_bytes + 32
    ratio = fused_bytes / split_bytes
    print(
        json.dumps(
            {
                "metric": "fused_reduction_hbm_traffic_ratio",
                "value": round(ratio, 4),
                "unit": "fused/split segment bytes",
                "vs_baseline": round(0.55 / ratio, 3),
                "split_bytes": split_bytes,
                "fused_bytes": fused_bytes,
                "parity": list(fused),
            }
        )
    )


def _pctile(vals: list[float], q: float) -> float:
    """Nearest-rank percentile (no interpolation: a latency sample that
    happened is reported, one that didn't is not)."""
    s = sorted(vals)
    return s[min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))]


def service_latency_metric() -> None:
    """Service-plane latency line (runs on any platform): p50/p95 ms per
    op from the ``rpc.query`` spans of a mixed workload — hot index
    prefix counts, windowed counts through the materialize tier, and
    cold queries past covered_hi that exercise the batched cold plane.
    Every reply is asserted exact against a host oracle first."""
    import tempfile

    import numpy as np

    from sieve import trace
    from sieve.config import SieveConfig
    from sieve.coordinator import run_local
    from sieve.seed import seed_primes
    from sieve.service import ServiceClient, ServiceSettings, SieveService

    n = 2_000_000
    chunk = 1 << 18
    oracle = seed_primes(n + 9 * chunk)

    def o_pi(x: int) -> int:
        return int(np.searchsorted(oracle, x, side="right"))

    with tempfile.TemporaryDirectory(prefix="sieve_bench_svc") as ck:
        cfg = SieveConfig(
            n=n, backend="cpu-numpy", packing="odds", n_segments=8,
            checkpoint_dir=ck, quiet=True,
        )
        run_local(cfg)
        trace.enable()
        trace.drain_events()  # only this workload's spans are measured
        settings = ServiceSettings(
            workers=4, queue_limit=64, cold_chunk=chunk, refresh_s=0.0,
        )
        with SieveService(cfg, settings) as svc, \
                ServiceClient(svc.addr, timeout_s=60) as cli:
            for i in range(150):  # hot: O(log segments) prefix counts
                x = (7919 * (i + 1)) % n
                assert cli.pi(x) == o_pi(x), f"pi({x}) parity failure"
            for i in range(50):   # hot: windowed counts (materialize tier)
                lo = (104_729 * (i + 1)) % (n - 60_000)
                want = o_pi(lo + 50_000 - 1) - o_pi(lo - 1)
                assert cli.count(lo, lo + 50_000) == want, \
                    f"count({lo}) parity failure"
            for i in range(8):    # cold: one fresh chunk each, batched
                x = n + (i + 1) * chunk - 1
                assert cli.pi(x) == o_pi(x), f"cold pi({x}) parity failure"
        events, _dropped = trace.drain_events()
        trace.disable()
    by_op: dict[str, list[float]] = {}
    for e in events:
        if e.get("name") == "rpc.query":
            op = (e.get("args") or {}).get("op", "?")
            by_op.setdefault(op, []).append(e["dur"] / 1000.0)  # us -> ms
    assert by_op, "no rpc.query spans captured"
    all_ms = [v for vals in by_op.values() for v in vals]
    p95 = _pctile(all_ms, 0.95)
    budget_ms = 50.0
    print(
        json.dumps(
            {
                "metric": "service_query_latency_p95_ms",
                "value": round(p95, 3),
                "unit": "ms_p95",
                "vs_baseline": round(budget_ms / p95, 3) if p95 else None,
                "p50_ms": round(_pctile(all_ms, 0.5), 3),
                "ops": {
                    op: {
                        "n": len(vals),
                        "p50_ms": round(_pctile(vals, 0.5), 3),
                        "p95_ms": round(_pctile(vals, 0.95), 3),
                    }
                    for op, vals in sorted(by_op.items())
                },
            }
        )
    )


def service_hot_qps_metric() -> None:
    """Wire-plane throughput line (ISSUE 14 tentpole gate): hot-query
    throughput on ONE replica, three ways over the same 256 hot prefix
    queries — sequential (one request in flight, the pre-ISSUE-14
    ceiling), pipelined (submit/drain on one connection), and batched
    (one ``batch`` RPC per 256 members, answered by a single vectorized
    ``np.searchsorted`` row). Every answer is asserted exact against a
    host oracle. ``service_hot_qps`` is the batched number; its
    ``vs_baseline`` is batched/sequential and the acceptance bar is
    >=10x at a sequential hot p95 no worse than BENCH_r09's. Gated
    round-over-round by tools/bench_compare.py's ``qps`` rule.

    The three legs above run on a ``negotiate=False`` client so the
    ``service_hot_qps`` line keeps measuring the JSON v1 wire it always
    measured. A second, negotiated connection then re-runs the batched
    loop through the binary columnar frames (ISSUE 16) and emits
    ``service_hot_qps_binary`` plus ``service_wire_bytes_per_member``
    (sent+received bytes per batch member, binary vs JSON — gated by an
    absolute ceiling in bench_compare, like the overhead ratios)."""
    import tempfile

    import numpy as np

    from sieve.config import SieveConfig
    from sieve.coordinator import run_local
    from sieve.seed import seed_primes
    from sieve.service import ServiceClient, ServiceSettings, SieveService

    n = 2_000_000
    chunk = 1 << 18
    oracle = seed_primes(n + chunk)

    def o_pi(x: int) -> int:
        return int(np.searchsorted(oracle, x, side="right"))

    xs = [(7919 * (i + 1)) % n for i in range(256)]
    want = [o_pi(x) for x in xs]

    with tempfile.TemporaryDirectory(prefix="sieve_bench_qps") as ck:
        cfg = SieveConfig(
            n=n, backend="cpu-numpy", packing="odds", n_segments=8,
            checkpoint_dir=ck, quiet=True,
        )
        run_local(cfg)
        settings = ServiceSettings(
            # queue sized for the 256-deep pipeline: this line measures
            # the wire plane, not admission control (ISSUE 10 benches
            # keep the small-queue shed behavior honest)
            workers=4, queue_limit=512, cold_chunk=chunk, refresh_s=0.0,
        )
        with SieveService(cfg, settings) as svc, \
                ServiceClient(svc.addr, timeout_s=60,
                              negotiate=False) as cli:
            for x, w in zip(xs[:64], want[:64]):  # warm index/LRU paths
                assert cli.pi(x) == w, f"warm pi({x}) parity failure"

            # sequential baseline: one request in flight, client-side
            # per-call latency measured for the hot p95 guard
            lat_ms: list[float] = []
            t0 = time.perf_counter()
            for x, w in zip(xs, want):
                c0 = time.perf_counter()
                assert cli.pi(x) == w, f"seq pi({x}) parity failure"
                lat_ms.append((time.perf_counter() - c0) * 1000.0)
            seq_s = time.perf_counter() - t0
            seq_qps = len(xs) / seq_s

            # pipelined: submit all 256 on one connection, then drain
            reps_p = 8
            t0 = time.perf_counter()
            for _ in range(reps_p):
                ids = [cli.submit("pi", x=x) for x in xs]
                replies = cli.drain(ids)
                for rid, w in zip(ids, want):
                    assert replies[rid].get("ok") and \
                        replies[rid]["value"] == w, \
                        f"pipelined pi parity failure: {replies[rid]!r}"
            pipe_qps = reps_p * len(xs) / (time.perf_counter() - t0)

            # batched: one RPC per 256 members, one vectorized gather
            items = [{"op": "pi", "x": x} for x in xs]
            reps_b = 40
            t0 = time.perf_counter()
            for _ in range(reps_b):
                outs = cli.query_batch(items)
                for o, w in zip(outs, want):
                    assert o.get("ok") and o["value"] == w, \
                        f"batch pi parity failure: {o!r}"
            batch_qps = reps_b * len(xs) / (time.perf_counter() - t0)

            # JSON wire cost for the bytes-per-member comparison: one
            # batch with the counters read around it
            js0, jr0 = cli.bytes_sent, cli.bytes_recv
            cli.query_batch(items)
            json_bpm = (cli.bytes_sent - js0 + cli.bytes_recv - jr0) \
                / len(xs)

            # binary wire v2 (ISSUE 16): same members, same oracle, on a
            # freshly negotiated connection — columnar frames end-to-end
            with ServiceClient(svc.addr, timeout_s=60) as cli2:
                assert cli2.wire_v == 2, "binary v2 negotiation failed"
                lat2_ms: list[float] = []
                for x, w in zip(xs, want):
                    c0 = time.perf_counter()
                    assert cli2.pi(x) == w, \
                        f"v2 seq pi({x}) parity failure"
                    lat2_ms.append((time.perf_counter() - c0) * 1000.0)
                t0 = time.perf_counter()
                for _ in range(reps_b):
                    outs = cli2.query_batch(items)
                    for o, w in zip(outs, want):
                        assert o.get("ok") and o["value"] == w, \
                            f"v2 batch pi parity failure: {o!r}"
                bin_qps = reps_b * len(xs) / (time.perf_counter() - t0)
                bs0, br0 = cli2.bytes_sent, cli2.bytes_recv
                cli2.query_batch(items)
                bin_bpm = (cli2.bytes_sent - bs0 + cli2.bytes_recv
                           - br0) / len(xs)

    hot_p95 = _pctile(lat_ms, 0.95)
    print(
        json.dumps(
            {
                "metric": "service_hot_qps",
                "value": round(batch_qps, 1),
                "unit": "qps",
                "vs_baseline": round(batch_qps / seq_qps, 2),
                "sequential_qps": round(seq_qps, 1),
                "pipeline_qps": round(pipe_qps, 1),
                "hot_p95_ms": round(hot_p95, 3),
                "queries": reps_b * len(xs),
            }
        )
    )
    print(
        json.dumps(
            {
                "metric": "service_pipeline_qps",
                "value": round(pipe_qps, 1),
                "unit": "qps",
                "vs_baseline": round(pipe_qps / seq_qps, 2),
                "queries": reps_p * len(xs),
            }
        )
    )
    print(
        json.dumps(
            {
                "metric": "service_hot_qps_binary",
                "value": round(bin_qps, 1),
                "unit": "qps",
                "vs_json": round(bin_qps / batch_qps, 2),
                "hot_p95_ms": round(_pctile(lat2_ms, 0.95), 3),
                "queries": reps_b * len(xs),
            }
        )
    )
    print(
        json.dumps(
            {
                "metric": "service_wire_bytes_per_member",
                "value": round(bin_bpm, 1),
                "unit": "bytes_per_member",
                "json_bytes_per_member": round(json_bpm, 1),
                "vs_json": round(bin_bpm / json_bpm, 2),
            }
        )
    )


def service_hot_qps_scaling_metric() -> None:
    """Multi-process scaling metric (ISSUE 17): hot qps at --procs 1, 2
    and 4 on ONE port, all processes sharing the mmap'd segment store.

    Python threads share one GIL, so the single-process hot ceiling is
    roughly one core; SO_REUSEPORT processes are the escape hatch. The
    recorded value is the incremental efficiency q4 / (4 * q1) — gated
    by tools/bench_compare.py's ``scaling_ratio`` rule at >= 0.7x per
    added process, enforced only on hosts with at least ``procs_max``
    CPUs (``cpus`` rides the record: on a 1-core container the extra
    processes time-slice one core and the ratio measures the scheduler,
    not the architecture). Every reply is asserted oracle-exact.
    """
    import os
    import signal
    import subprocess
    import tempfile
    import threading

    import numpy as np

    from sieve.config import SieveConfig
    from sieve.coordinator import run_local
    from sieve.seed import seed_primes
    from sieve.service import ServiceClient

    n = 1_000_000
    oracle = seed_primes(n + 1)

    def o_pi(x: int) -> int:
        return int(np.searchsorted(oracle, x, side="right"))

    xs = [(7919 * (i + 1)) % n for i in range(128)]
    want = [o_pi(x) for x in xs]
    reps = 3  # per-thread passes over xs in the timed window
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    qps: dict[int, float] = {}
    with tempfile.TemporaryDirectory(prefix="sieve_bench_scale") as ck:
        cfg = SieveConfig(
            n=n, backend="cpu-numpy", packing="odds", n_segments=8,
            checkpoint_dir=ck, quiet=True,
        )
        run_local(cfg)
        for procs in (1, 2, 4):
            cmd = [sys.executable, "-m", "sieve", "serve", "--n", str(n),
                   "--segments", "8", "--checkpoint-dir", ck,
                   "--addr", "127.0.0.1:0", "--procs", str(procs),
                   "--quiet"]
            proc = subprocess.Popen(cmd, env=env, cwd=repo,
                                    stdout=subprocess.PIPE, text=True)
            assert proc.stdout is not None
            doc = json.loads(proc.stdout.readline())
            assert doc.get("event") == "serving", doc
            addr = doc["addr"]
            try:
                # warm every process's index/LRU: fresh connections
                # spread over the fleet until each answered some
                for _ in range(max(4, 2 * procs)):
                    with ServiceClient(addr, timeout_s=60) as c:
                        for x, w in zip(xs[:32], want[:32]):
                            assert c.pi(x) == w, \
                                f"warm pi({x}) parity failure"

                errs: list[BaseException] = []

                def pump() -> None:
                    try:
                        with ServiceClient(addr, timeout_s=60) as c:
                            for _ in range(reps):
                                for x, w in zip(xs, want):
                                    assert c.pi(x) == w, \
                                        f"pi({x}) parity failure"
                    except BaseException as e:  # noqa: BLE001
                        errs.append(e)

                threads = [threading.Thread(target=pump)
                           for _ in range(procs)]
                t0 = time.perf_counter()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(300)
                elapsed = time.perf_counter() - t0
                assert not errs, f"scaling pump failed: {errs[0]!r}"
                assert not any(t.is_alive() for t in threads), \
                    "scaling pump hung"
                qps[procs] = procs * reps * len(xs) / elapsed
            finally:
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc.kill()

    ratio = qps[4] / (4 * qps[1])
    print(
        json.dumps(
            {
                "metric": "service_hot_qps_scaling",
                "value": round(ratio, 3),
                "unit": "scaling_ratio",
                "qps_1": round(qps[1], 1),
                "qps_2": round(qps[2], 1),
                "qps_4": round(qps[4], 1),
                "procs_max": 4,
                "cpus": os.cpu_count(),
                "queries_per_proc": reps * len(xs),
            }
        )
    )


def service_hot_under_flood_metric() -> None:
    """Priority-lane metric (ISSUE 10): hot-query p95 while a 20-thread
    cold flood saturates the backend plane (``cold_delay_s`` simulated).
    Gated by tools/bench_compare.py's ``ms_p95`` rule: the number must
    not regress >10% round-over-round — the lane isolation guarantee as
    a benchmark. Every hot reply is asserted exact; cold replies must be
    exact or typed."""
    import tempfile
    import threading

    import numpy as np

    from sieve import trace
    from sieve.config import SieveConfig
    from sieve.coordinator import run_local
    from sieve.seed import seed_primes
    from sieve.service import ServiceClient, ServiceSettings, SieveService

    n = 2_000_000
    chunk = 1 << 18
    oracle = seed_primes(n + 24 * chunk)

    def o_pi(x: int) -> int:
        return int(np.searchsorted(oracle, x, side="right"))

    with tempfile.TemporaryDirectory(prefix="sieve_bench_flood") as ck:
        cfg = SieveConfig(
            n=n, backend="cpu-numpy", packing="odds", n_segments=8,
            checkpoint_dir=ck, quiet=True,
        )
        run_local(cfg)
        trace.enable()
        trace.drain_events()
        settings = ServiceSettings(
            workers=4, hot_workers=1, queue_limit=64, cold_queue_limit=16,
            cold_chunk=chunk, cold_delay_s=0.15, cold_age_s=0.5,
            default_deadline_s=30.0, refresh_s=0.0,
        )
        typed = {"overloaded", "deadline_exceeded", "degraded"}
        with SieveService(cfg, settings) as svc, \
                ServiceClient(svc.addr, timeout_s=60) as cli:
            for i in range(50):  # warm the hot path / LRU first
                x = (7919 * (i + 1)) % n
                assert cli.pi(x) == o_pi(x), f"warm pi({x}) parity failure"

            def flood(i: int) -> None:
                x = n + (i + 1) * chunk - 1  # distinct cold chunks
                with ServiceClient(svc.addr, timeout_s=60) as c:
                    rep = c.query("pi", x=x)
                    if rep.get("ok"):
                        assert rep["value"] == o_pi(x), \
                            f"cold pi({x}) parity failure"
                    else:
                        assert rep.get("error") in typed, \
                            f"cold pi({x}) untyped reply {rep!r}"

            threads = [threading.Thread(target=flood, args=(i,))
                       for i in range(20)]
            t_mark = trace.now_s()
            for t in threads:
                t.start()
            for _ in range(3):  # the hot stream the lanes must protect
                for i in range(50):
                    x = (7919 * (i + 1)) % n
                    assert cli.pi(x) == o_pi(x), \
                        f"hot pi({x}) parity failure"
            for t in threads:
                t.join(120)
        events, _dropped = trace.drain_events()
        trace.disable()
    hot_ms = [
        e["dur"] / 1000.0 for e in events
        if e.get("name") == "rpc.query"
        and (e.get("args") or {}).get("lane") == "hot"
        and e["ts"] / 1e6 >= t_mark  # flood window only, not the warmup
    ]
    assert hot_ms, "no hot-lane rpc.query spans captured under flood"
    p95 = _pctile(hot_ms, 0.95)
    budget_ms = 50.0
    print(
        json.dumps(
            {
                "metric": "service_hot_under_flood_ms_p95",
                "value": round(p95, 3),
                "unit": "ms_p95",
                "vs_baseline": round(budget_ms / p95, 3) if p95 else None,
                "p50_ms": round(_pctile(hot_ms, 0.5), 3),
                "hot_n": len(hot_ms),
            }
        )
    )


def router_query_latency_metric() -> None:
    """Router fabric metric (ISSUE 11): overall p95 ms from the
    ``rpc.route`` spans of a mixed workload against a two-shard
    in-process fabric. The source ledger is split 4+4 into per-shard
    serving dirs (shard 1 runs with ``range_lo``); the workload mixes
    point routes, scatter-gather prefix counts, windowed counts, and
    twin windows that straddle the shard edge so the splice path is in
    the measured distribution. Every reply is asserted exact against a
    host oracle before timing counts; the stats snapshot must show both
    full-shard totals cached and at least one splice."""
    import tempfile

    import numpy as np

    from sieve import trace
    from sieve.checkpoint import Ledger
    from sieve.config import SieveConfig
    from sieve.coordinator import run_local
    from sieve.seed import seed_primes
    from sieve.service import (
        RouterSettings,
        ServiceClient,
        ServiceSettings,
        Shard,
        ShardMap,
        SieveRouter,
        SieveService,
    )

    n = 2_000_000
    oracle = seed_primes(n + 100_000)

    def o_pi(x: int) -> int:
        return int(np.searchsorted(oracle, x, side="right"))

    def o_count(lo: int, hi: int) -> int:
        return int(np.searchsorted(oracle, hi, side="left")
                   - np.searchsorted(oracle, lo, side="left"))

    def o_pairs(lo: int, hi: int, gap: int) -> int:
        w = oracle[(oracle >= lo) & (oracle < hi)]
        if w.size < 2:
            return 0
        idx = np.searchsorted(w, w + gap)
        ok = idx < w.size
        return int(np.count_nonzero(w[idx[ok]] == w[ok] + gap))

    def shard_cfg(d: str) -> SieveConfig:
        return SieveConfig(
            n=n, backend="cpu-numpy", packing="odds", n_segments=8,
            checkpoint_dir=d, quiet=True,
        )

    with tempfile.TemporaryDirectory(prefix="sieve_bench_router") as ck:
        src = os.path.join(ck, "src")
        run_local(shard_cfg(src))
        segs = sorted(
            Ledger.open_readonly(shard_cfg(src)).completed().values(),
            key=lambda r: r.lo,
        )
        E = segs[4].lo  # shard edge on a segment boundary
        dirs = [os.path.join(ck, f"shard{i}") for i in range(2)]
        for d, part in zip(dirs, (segs[:4], segs[4:])):
            led = Ledger.open(shard_cfg(d))
            for r in part:
                led.record(r)

        trace.enable()
        trace.drain_events()  # only this workload's spans are measured
        svcs = [
            SieveService(
                shard_cfg(dirs[0]),
                ServiceSettings(workers=4, queue_limit=64, refresh_s=0.0),
            ).start(),
            SieveService(
                shard_cfg(dirs[1]),
                ServiceSettings(workers=4, queue_limit=64, refresh_s=0.0,
                                range_lo=E),
            ).start(),
        ]
        smap = ShardMap([
            Shard(2, E, (svcs[0].addr,)),
            Shard(E, n + 1, (svcs[1].addr,)),
        ])
        router = SieveRouter(smap, RouterSettings(quiet=True)).start()
        try:
            with ServiceClient(router.addr, timeout_s=60) as cli:
                # full-range prefix: caches BOTH full-shard totals
                assert cli.pi(n) == o_pi(n), f"pi({n}) parity failure"
                for i in range(120):  # scatter-gather prefix counts
                    x = (7919 * (i + 1)) % n
                    assert cli.pi(x) == o_pi(x), f"pi({x}) parity failure"
                for i in range(60):   # windowed counts, both shards
                    lo = (104_729 * (i + 1)) % (n - 60_000)
                    want = o_count(lo, lo + 50_000)
                    assert cli.count(lo, lo + 50_000) == want, \
                        f"count({lo}) parity failure"
                for i in range(40):   # point routes to one shard each
                    x = (7907 * (i + 1)) % n
                    got = cli.query("is_prime", x=x)
                    assert got["ok"] and got["value"] == (o_count(x, x + 1) == 1), \
                        f"is_prime({x}) parity failure"
                for i in range(30):   # edge-straddling pair windows: splice
                    lo, hi = E - 400 - 37 * i, E + 400 + 29 * i
                    rep = cli.query("count", lo=lo, hi=hi, kind="twins")
                    assert rep["ok"] and rep["value"] == o_pairs(lo, hi, 2), \
                        f"twins({lo},{hi}) parity failure"
                for i in range(10):   # nth_prime walks the cumulative totals
                    k = o_pi(E - 1) - 5 + i  # straddles the edge count
                    rep = cli.query("nth_prime", k=k)
                    assert rep["ok"] and rep["value"] == int(oracle[k - 1]), \
                        f"nth_prime({k}) parity failure"
                st = cli.stats()
                assert st["totals_cached"] == 2, "full-shard totals not cached"
                assert st["spliced"] >= 1, "no edge splice in the workload"
        finally:
            router.stop()
            for s in svcs:
                s.stop()
        events, _dropped = trace.drain_events()
        trace.disable()
    by_op: dict[str, list[float]] = {}
    for e in events:
        if e.get("name") == "rpc.route":
            op = (e.get("args") or {}).get("op", "?")
            by_op.setdefault(op, []).append(e["dur"] / 1000.0)  # us -> ms
    assert by_op, "no rpc.route spans captured"
    all_ms = [v for vals in by_op.values() for v in vals]
    p95 = _pctile(all_ms, 0.95)
    budget_ms = 50.0
    print(
        json.dumps(
            {
                "metric": "router_query_latency_p95_ms",
                "value": round(p95, 3),
                "unit": "ms_p95",
                "vs_baseline": round(budget_ms / p95, 3) if p95 else None,
                "p50_ms": round(_pctile(all_ms, 0.5), 3),
                "ops": {
                    op: {
                        "n": len(vals),
                        "p50_ms": round(_pctile(vals, 0.5), 3),
                        "p95_ms": round(_pctile(vals, 0.95), 3),
                    }
                    for op, vals in sorted(by_op.items())
                },
            }
        )
    )


def service_trace_overhead_metric() -> None:
    """Fleet-tracing overhead (ISSUE 12): the line-5 mixed workload —
    hot prefix counts, windowed counts, and genuinely cold chunks, same
    shape and same cold behavior as ``service_query_latency_p95_ms`` —
    run against fresh in-process services with the trace plane fully
    off vs fully on (span capture + bounded ship ring + batched reply
    piggybacks), timed from the CLIENT side so the ratio includes the
    serialize/ship cost a server-side span would hide. The passes are
    INTERLEAVED (off, on, off, on, ...); each begins with a short
    untimed hot warmup (thread-name metadata, first counter-window
    samples, and allocator transients must not land in the timed tail),
    and the reported p95 per mode is the MINIMUM across reps — the
    converged noise floor, which still contains every deterministic
    per-request tracing cost. Every reply is asserted exact; every pass
    gets a fresh service (cold LRU), so cold chunks cost the same in
    both modes."""
    import tempfile

    import numpy as np

    from sieve import trace
    from sieve.config import SieveConfig
    from sieve.coordinator import run_local
    from sieve.seed import seed_primes
    from sieve.service import ServiceClient, ServiceSettings, SieveService

    n = 2_000_000
    chunk = 1 << 18
    reps = 25
    oracle = seed_primes(n + 9 * chunk)

    def o_pi(x: int) -> int:
        return int(np.searchsorted(oracle, x, side="right"))

    def workload(cli: ServiceClient, timings: list[float]) -> None:
        def timed(fn, *a):
            t0 = time.perf_counter()
            out = fn(*a)
            timings.append((time.perf_counter() - t0) * 1e3)
            return out

        for i in range(150):  # hot: prefix counts
            x = (7919 * (i + 1)) % n
            assert timed(cli.pi, x) == o_pi(x), f"pi({x}) parity failure"
        for i in range(50):   # hot: windowed counts (materialize tier)
            lo = (104_729 * (i + 1)) % (n - 60_000)
            want = o_pi(lo + 50_000 - 1) - o_pi(lo - 1)
            assert timed(cli.count, lo, lo + 50_000) == want, \
                f"count({lo}) parity failure"
        for i in range(8):    # cold: one fresh chunk each, batched
            x = n + (i + 1) * chunk - 1
            assert timed(cli.pi, x) == o_pi(x), f"cold pi({x}) parity"

    with tempfile.TemporaryDirectory(prefix="sieve_bench_trace") as ck:
        cfg = SieveConfig(
            n=n, backend="cpu-numpy", packing="odds", n_segments=8,
            checkpoint_dir=ck, quiet=True,
        )
        run_local(cfg)

        def run_pass(traced: bool) -> list[float]:
            settings = ServiceSettings(
                workers=4, queue_limit=64, cold_chunk=chunk,
                refresh_s=0.0, telemetry_ship=traced,
            )
            if traced:
                trace.enable()
            with SieveService(cfg, settings) as svc, \
                    ServiceClient(svc.addr, timeout_s=60) as cli:
                timings: list[float] = []
                if traced:
                    # ask for the piggyback like a tracing router would
                    orig = cli.query
                    cli.query = (  # type: ignore[method-assign]
                        lambda op, deadline_s=None, **p:
                        orig(op, deadline_s, telemetry=True, **p)
                    )
                for i in range(30):  # untimed warmup: steady state only
                    cli.pi((101 * (i + 1)) % n)
                workload(cli, timings)
            if traced:
                trace.drain_events()
                trace.disable()
                trace.set_event_limit(None)
            return timings

        p95s_off: list[float] = []
        p95s_on: list[float] = []
        n_reqs = 0
        for _ in range(reps):
            off = run_pass(traced=False)
            on = run_pass(traced=True)
            p95s_off.append(_pctile(off, 0.95))
            p95s_on.append(_pctile(on, 0.95))
            n_reqs = len(on)
    # min across reps per mode: the converged per-pass-p95 floor
    p95_off = min(p95s_off)
    p95_on = min(p95s_on)
    ratio = p95_on / p95_off if p95_off else float("inf")
    budget = 1.05
    print(
        json.dumps(
            {
                "metric": "service_trace_overhead_ratio",
                "value": round(ratio, 4),
                "unit": "overhead_ratio",
                "vs_baseline": round(budget / ratio, 3) if ratio else None,
                "p95_untraced_ms": round(p95_off, 3),
                "p95_traced_ms": round(p95_on, 3),
                "n": n_reqs,
                "reps": reps,
            }
        )
    )


def service_recorder_overhead_metric() -> None:
    """Flight-recorder overhead (ISSUE 13): the line-8 mixed workload
    and methodology — interleaved off/on passes, fresh service per
    pass, untimed warmup, client-side timing, min-across-reps p95 —
    with the black box as the variable instead of the trace plane:
    recorder armed (metrics-sink event tail + chained crash hooks)
    plus the MetricsHistory sampler ticking aggressively at 50 ms and
    a real ``--debug-dir``, vs both fully off. No trigger fires during
    the workload, so the ratio prices exactly what every production
    service pays in steady state: one deque append per metrics event
    and a background snapshot thread. Every reply asserted exact."""
    import tempfile

    import numpy as np

    from sieve.config import SieveConfig
    from sieve.coordinator import run_local
    from sieve.seed import seed_primes
    from sieve.service import ServiceClient, ServiceSettings, SieveService

    n = 2_000_000
    chunk = 1 << 18
    reps = 25
    oracle = seed_primes(n + 9 * chunk)

    def o_pi(x: int) -> int:
        return int(np.searchsorted(oracle, x, side="right"))

    def workload(cli: ServiceClient, timings: list[float]) -> None:
        def timed(fn, *a):
            t0 = time.perf_counter()
            out = fn(*a)
            timings.append((time.perf_counter() - t0) * 1e3)
            return out

        for i in range(150):  # hot: prefix counts
            x = (7919 * (i + 1)) % n
            assert timed(cli.pi, x) == o_pi(x), f"pi({x}) parity failure"
        for i in range(50):   # hot: windowed counts (materialize tier)
            lo = (104_729 * (i + 1)) % (n - 60_000)
            want = o_pi(lo + 50_000 - 1) - o_pi(lo - 1)
            assert timed(cli.count, lo, lo + 50_000) == want, \
                f"count({lo}) parity failure"
        for i in range(8):    # cold: one fresh chunk each, batched
            x = n + (i + 1) * chunk - 1
            assert timed(cli.pi, x) == o_pi(x), f"cold pi({x}) parity"

    with tempfile.TemporaryDirectory(prefix="sieve_bench_rec") as ck, \
            tempfile.TemporaryDirectory(prefix="sieve_bench_dbg") as dbg:
        cfg = SieveConfig(
            n=n, backend="cpu-numpy", packing="odds", n_segments=8,
            checkpoint_dir=ck, quiet=True,
        )
        run_local(cfg)

        def run_pass(recorded: bool) -> list[float]:
            settings = ServiceSettings(
                workers=4, queue_limit=64, cold_chunk=chunk,
                refresh_s=0.0, recorder=recorded,
                debug_dir=dbg if recorded else None,
                # 20 samples/s: far denser than the 1 s production
                # default, so the sampler genuinely runs in-window
                metrics_sample_s=0.05 if recorded else 0.0,
            )
            with SieveService(cfg, settings) as svc, \
                    ServiceClient(svc.addr, timeout_s=60) as cli:
                timings: list[float] = []
                for i in range(30):  # untimed warmup: steady state only
                    cli.pi((101 * (i + 1)) % n)
                workload(cli, timings)
            return timings

        p95s_off: list[float] = []
        p95s_on: list[float] = []
        n_reqs = 0
        for _ in range(reps):
            off = run_pass(recorded=False)
            on = run_pass(recorded=True)
            p95s_off.append(_pctile(off, 0.95))
            p95s_on.append(_pctile(on, 0.95))
            n_reqs = len(on)
    p95_off = min(p95s_off)
    p95_on = min(p95s_on)
    ratio = p95_on / p95_off if p95_off else float("inf")
    budget = 1.05
    print(
        json.dumps(
            {
                "metric": "service_recorder_overhead_ratio",
                "value": round(ratio, 4),
                "unit": "overhead_ratio",
                "vs_baseline": round(budget / ratio, 3) if ratio else None,
                "p95_unrecorded_ms": round(p95_off, 3),
                "p95_recorded_ms": round(p95_on, 3),
                "n": n_reqs,
                "reps": reps,
            }
        )
    )


def service_profiler_overhead_metric() -> None:
    """Continuous-profiler overhead (ISSUE 20): the line-8 mixed
    workload and methodology — interleaved off/on passes, fresh
    service per pass, untimed warmup, client-side timing,
    min-across-reps p95 — with the always-on statistical sampler as
    the variable: ``prof_hz`` at the production default (19 Hz daemon
    walking ``sys._current_frames()`` and folding into the bounded
    collapsed-stack table) vs 0 (no sampler thread at all). Nothing
    pulls the profile during the workload, so the ratio prices
    exactly the steady-state tax of leaving the sampler on in every
    server and router. Every reply asserted exact. Budget: 1.05 —
    same bar as the trace and recorder planes; always-on means
    nobody can measure it."""
    import tempfile

    import numpy as np

    from sieve.config import SieveConfig
    from sieve.coordinator import run_local
    from sieve.seed import seed_primes
    from sieve.service import ServiceClient, ServiceSettings, SieveService

    n = 2_000_000
    chunk = 1 << 18
    reps = 25
    oracle = seed_primes(n + 9 * chunk)

    def o_pi(x: int) -> int:
        return int(np.searchsorted(oracle, x, side="right"))

    def workload(cli: ServiceClient, timings: list[float]) -> None:
        def timed(fn, *a):
            t0 = time.perf_counter()
            out = fn(*a)
            timings.append((time.perf_counter() - t0) * 1e3)
            return out

        for i in range(150):  # hot: prefix counts
            x = (7919 * (i + 1)) % n
            assert timed(cli.pi, x) == o_pi(x), f"pi({x}) parity failure"
        for i in range(50):   # hot: windowed counts (materialize tier)
            lo = (104_729 * (i + 1)) % (n - 60_000)
            want = o_pi(lo + 50_000 - 1) - o_pi(lo - 1)
            assert timed(cli.count, lo, lo + 50_000) == want, \
                f"count({lo}) parity failure"
        for i in range(8):    # cold: one fresh chunk each, batched
            x = n + (i + 1) * chunk - 1
            assert timed(cli.pi, x) == o_pi(x), f"cold pi({x}) parity"

    with tempfile.TemporaryDirectory(prefix="sieve_bench_prof") as ck:
        cfg = SieveConfig(
            n=n, backend="cpu-numpy", packing="odds", n_segments=8,
            checkpoint_dir=ck, quiet=True,
        )
        run_local(cfg)

        def run_pass(profiled: bool) -> list[float]:
            settings = ServiceSettings(
                workers=4, queue_limit=64, cold_chunk=chunk,
                refresh_s=0.0,
                prof_hz=19.0 if profiled else 0.0,
            )
            with SieveService(cfg, settings) as svc, \
                    ServiceClient(svc.addr, timeout_s=60) as cli:
                timings: list[float] = []
                for i in range(30):  # untimed warmup: steady state only
                    cli.pi((101 * (i + 1)) % n)
                workload(cli, timings)
            return timings

        p95s_off: list[float] = []
        p95s_on: list[float] = []
        n_reqs = 0
        for _ in range(reps):
            off = run_pass(profiled=False)
            on = run_pass(profiled=True)
            p95s_off.append(_pctile(off, 0.95))
            p95s_on.append(_pctile(on, 0.95))
            n_reqs = len(on)
    p95_off = min(p95s_off)
    p95_on = min(p95s_on)
    ratio = p95_on / p95_off if p95_off else float("inf")
    budget = 1.05
    print(
        json.dumps(
            {
                "metric": "service_profiler_overhead_ratio",
                "value": round(ratio, 4),
                "unit": "overhead_ratio",
                "vs_baseline": round(budget / ratio, 3) if ratio else None,
                "p95_unprofiled_ms": round(p95_off, 3),
                "p95_profiled_ms": round(p95_on, 3),
                "n": n_reqs,
                "reps": reps,
            }
        )
    )


def service_lock_debug_overhead_metric() -> None:
    """Lock-sanitizer overhead (ISSUE 15): the same interleaved
    off/on, fresh-service-per-pass, untimed-warmup, client-side,
    min-across-reps p95 methodology as the trace/recorder overhead
    lines, with ``SIEVE_LOCK_DEBUG`` as the variable. The flag is read
    once at lock *construction* (``sieve/analysis/lockdebug.py``), so
    the off pass prices the production default — plain ``threading``
    primitives, zero wrapper code on the hot path — and the on pass
    prices the recording wrappers (a thread-local stack walk plus a
    pair-dict fold under the recorder mutex on every acquisition,
    across every named lock in service, client, index, and metrics).
    The workload is the same mixed line the other two overhead
    metrics time — hot prefix counts, windowed counts, genuinely cold
    chunks — so the three ratios stay comparable. A hot ``pi`` does
    ~50 recorded acquisitions; the wrappers' cost lands inside those
    critical sections, so contention amplifies it at p95. The on
    passes end by asserting the observed orders against
    ``CANONICAL_LOCK_ORDER`` — the bench run doubles as a sanitizer
    smoke. Budget: 1.10 (the other overhead lines get 1.05; this one
    wraps every lock in the plane and is a debug mode, not an
    always-on tax)."""
    import tempfile

    import numpy as np

    from sieve.analysis import lockdebug
    from sieve.config import SieveConfig
    from sieve.coordinator import run_local
    from sieve.seed import seed_primes
    from sieve.service import ServiceClient, ServiceSettings, SieveService

    n = 2_000_000
    chunk = 1 << 18
    reps = 25
    oracle = seed_primes(n + 9 * chunk)

    def o_pi(x: int) -> int:
        return int(np.searchsorted(oracle, x, side="right"))

    def workload(cli: ServiceClient, timings: list[float]) -> None:
        def timed(fn, *a):
            t0 = time.perf_counter()
            out = fn(*a)
            timings.append((time.perf_counter() - t0) * 1e3)
            return out

        for i in range(150):  # hot: prefix counts
            x = (7919 * (i + 1)) % n
            assert timed(cli.pi, x) == o_pi(x), f"pi({x}) parity failure"
        for i in range(50):   # hot: windowed counts (materialize tier)
            lo = (104_729 * (i + 1)) % (n - 60_000)
            want = o_pi(lo + 50_000 - 1) - o_pi(lo - 1)
            assert timed(cli.count, lo, lo + 50_000) == want, \
                f"count({lo}) parity failure"
        for i in range(8):    # cold: one fresh chunk each, batched
            x = n + (i + 1) * chunk - 1
            assert timed(cli.pi, x) == o_pi(x), f"cold pi({x}) parity"

    with tempfile.TemporaryDirectory(prefix="sieve_bench_lockdbg") as ck:
        cfg = SieveConfig(
            n=n, backend="cpu-numpy", packing="odds", n_segments=8,
            checkpoint_dir=ck, quiet=True,
        )
        run_local(cfg)

        def run_pass(debug: bool) -> list[float]:
            # construction-time flag: set before the service (and the
            # client pool) build their locks, restore after
            prev = os.environ.pop("SIEVE_LOCK_DEBUG", None)
            if debug:
                os.environ["SIEVE_LOCK_DEBUG"] = "1"
                lockdebug.recorder().reset()
            try:
                settings = ServiceSettings(
                    workers=4, queue_limit=64, cold_chunk=chunk,
                    refresh_s=0.0,
                )
                with SieveService(cfg, settings) as svc, \
                        ServiceClient(svc.addr, timeout_s=60) as cli:
                    timings: list[float] = []
                    for i in range(30):  # untimed warmup
                        cli.pi((101 * (i + 1)) % n)
                    workload(cli, timings)
            finally:
                if prev is None:
                    os.environ.pop("SIEVE_LOCK_DEBUG", None)
                else:
                    os.environ["SIEVE_LOCK_DEBUG"] = prev
            if debug:
                problems = lockdebug.check_static_consistency()
                assert not problems, \
                    "lock sanitizer vs static graph: " + "; ".join(problems)
            return timings

        p95s_off: list[float] = []
        p95s_on: list[float] = []
        n_reqs = 0
        for _ in range(reps):
            off = run_pass(debug=False)
            on = run_pass(debug=True)
            p95s_off.append(_pctile(off, 0.95))
            p95s_on.append(_pctile(on, 0.95))
            n_reqs = len(on)
    p95_off = min(p95s_off)
    p95_on = min(p95s_on)
    ratio = p95_on / p95_off if p95_off else float("inf")
    budget = 1.10
    print(
        json.dumps(
            {
                "metric": "service_lock_debug_overhead_ratio",
                "value": round(ratio, 4),
                "unit": "overhead_ratio",
                "vs_baseline": round(budget / ratio, 3) if ratio else None,
                "p95_plain_ms": round(p95_off, 3),
                "p95_debug_ms": round(p95_on, 3),
                "n": n_reqs,
                "reps": reps,
            }
        )
    )


def service_observer_overhead_metric() -> None:
    """Capacity-observatory overhead (ISSUE 19): the line-8 mixed
    workload and methodology — interleaved off/on passes, fresh service
    per pass, untimed warmup, client-side timing, min-across-reps p95 —
    with the whole observatory as the variable: ON arms always-on
    exemplar tail sampling (span ring + completion-time sampler + the
    rolling exemplar file under a real debug dir) AND a live
    :class:`FleetObserver` scraping the service's health+stats at 1 Hz
    into an on-disk snapshot ring; OFF disables both. Both passes run
    with the flight recorder armed (its cost is line 9's ratio — this
    line prices only the NEW machinery on top). Nothing alarms during
    the workload (warmup exceeds a pass's scrape count), so the ratio
    is exactly the steady-state tax every observed production fleet
    pays. Every reply asserted exact."""
    import tempfile

    import numpy as np

    from sieve.config import SieveConfig
    from sieve.coordinator import run_local
    from sieve.seed import seed_primes
    from sieve.service import ServiceClient, ServiceSettings, SieveService
    from sieve.service.observe import FleetObserver, ObserverSettings

    n = 2_000_000
    chunk = 1 << 18
    reps = 25
    oracle = seed_primes(n + 9 * chunk)

    def o_pi(x: int) -> int:
        return int(np.searchsorted(oracle, x, side="right"))

    def workload(cli: ServiceClient, timings: list[float]) -> None:
        def timed(fn, *a):
            t0 = time.perf_counter()
            out = fn(*a)
            timings.append((time.perf_counter() - t0) * 1e3)
            return out

        for i in range(150):  # hot: prefix counts
            x = (7919 * (i + 1)) % n
            assert timed(cli.pi, x) == o_pi(x), f"pi({x}) parity failure"
        for i in range(50):   # hot: windowed counts (materialize tier)
            lo = (104_729 * (i + 1)) % (n - 60_000)
            want = o_pi(lo + 50_000 - 1) - o_pi(lo - 1)
            assert timed(cli.count, lo, lo + 50_000) == want, \
                f"count({lo}) parity failure"
        for i in range(8):    # cold: one fresh chunk each, batched
            x = n + (i + 1) * chunk - 1
            assert timed(cli.pi, x) == o_pi(x), f"cold pi({x}) parity"

    with tempfile.TemporaryDirectory(prefix="sieve_bench_obs_ck") as ck, \
            tempfile.TemporaryDirectory(prefix="sieve_bench_obs_dbg") as dbg:
        cfg = SieveConfig(
            n=n, backend="cpu-numpy", packing="odds", n_segments=8,
            checkpoint_dir=ck, quiet=True,
        )
        run_local(cfg)

        def run_pass(observed: bool) -> list[float]:
            sub = os.path.join(dbg, "on" if observed else "off")
            settings = ServiceSettings(
                workers=4, queue_limit=64, cold_chunk=chunk,
                refresh_s=0.0, exemplars=observed, debug_dir=sub,
            )
            with SieveService(cfg, settings) as svc, \
                    ServiceClient(svc.addr, timeout_s=60) as cli:
                obs = None
                if observed:
                    obs = FleetObserver(svc.addr, ObserverSettings(
                        scrape_s=1.0, observe_dir=sub, debug_pull=False,
                        quiet=True,
                    ))
                    obs.start()
                try:
                    timings: list[float] = []
                    for i in range(30):  # untimed warmup
                        cli.pi((101 * (i + 1)) % n)
                    workload(cli, timings)
                finally:
                    if obs is not None:
                        obs.stop()
            return timings

        p95s_off: list[float] = []
        p95s_on: list[float] = []
        n_reqs = 0
        for _ in range(reps):
            off = run_pass(observed=False)
            on = run_pass(observed=True)
            p95s_off.append(_pctile(off, 0.95))
            p95s_on.append(_pctile(on, 0.95))
            n_reqs = len(on)
    p95_off = min(p95s_off)
    p95_on = min(p95s_on)
    ratio = p95_on / p95_off if p95_off else float("inf")
    budget = 1.05
    print(
        json.dumps(
            {
                "metric": "service_observer_overhead_ratio",
                "value": round(ratio, 4),
                "unit": "overhead_ratio",
                "vs_baseline": round(budget / ratio, 3) if ratio else None,
                "p95_unobserved_ms": round(p95_off, 3),
                "p95_observed_ms": round(p95_on, 3),
                "n": n_reqs,
                "reps": reps,
            }
        )
    )


def service_cold_drain_throughput_metric() -> None:
    """Mesh cold-plane drain throughput (ISSUE 18): values/s through one
    drain slice of equal-span cold chunks on the mesh backend (ONE
    shard_map SPMD launch spanning every device) vs the loop backend (K
    sequential jax markings — what ``--cold-backend loop`` runs per
    drain). Runs tools/mesh_cold_smoke.py in a subprocess so the 8-way
    virtual CPU mesh (``XLA_FLAGS``) is forced before jax initializes —
    this process may already hold a single-device jax. The smoke
    parity-asserts mesh vs cpu-numpy vs a direct oracle before any
    number is printed, and fails unless the drain cost exactly one SPMD
    launch. Unit ``cold_throughput`` — gated against drops by
    tools/bench_compare.py; vs_baseline = mesh/loop speedup, so >= 1
    means one drain beats K markings. Host-only: emitted anywhere."""
    import subprocess

    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # let the smoke force its 8-device mesh
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "mesh_cold_smoke.py")],
        env=env, cwd=repo, capture_output=True, text=True, timeout=600,
    )
    if proc.returncode != 0 or "MESH_COLD_SMOKE_OK" not in proc.stdout:
        print(
            f"cold drain metric skipped: mesh smoke failed "
            f"(rc={proc.returncode})\n{proc.stdout[-2000:]}"
            f"{proc.stderr[-2000:]}",
            file=sys.stderr,
        )
        return
    for line in proc.stdout.splitlines():
        if line.startswith("{") and "service_cold_drain_throughput" in line:
            print(line)
            return


def main() -> int:
    shallow_metric()
    depth_metric()
    host_prepare_metric()
    fused_reduction_metric()
    service_latency_metric()
    service_hot_qps_metric()
    service_hot_qps_scaling_metric()
    service_hot_under_flood_metric()
    router_query_latency_metric()
    service_trace_overhead_metric()
    service_recorder_overhead_metric()
    service_profiler_overhead_metric()
    service_lock_debug_overhead_metric()
    service_observer_overhead_metric()
    service_cold_drain_throughput_metric()
    return 0


if __name__ == "__main__":
    sys.exit(main())
