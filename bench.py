"""Benchmark: pi(1e9), odds packing, jax backend on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: BASELINE.md's measured CPU floor — pi(1e9) segmented numpy in
7.5 s single process == 1.33e8 values/s. vs_baseline is the speedup of
this run's values/s over that floor. Exact pi parity is asserted before
any number is printed: a fast wrong sieve scores zero.
"""

from __future__ import annotations

import json
import os
import sys
import time

# persistent XLA compile cache: cuts repeat bench runs from minutes to seconds
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

N = 10**9
PI_N = 50_847_534  # BASELINE.md oracle (computed, 2026-07-29)
BASELINE_VALUES_PER_SEC = (N - 1) / 7.5  # BASELINE.md CPU floor


def main() -> int:
    from sieve.config import SieveConfig
    from sieve.coordinator import run_local

    cfg = SieveConfig(
        n=N, backend="tpu-pallas", packing="odds", n_segments=1, twins=False,
        quiet=True,
    )
    # warmup: compile every shape bucket once (first TPU compile is slow and
    # is not the thing being measured)
    warm = run_local(cfg)
    assert warm.pi == PI_N, f"warmup parity failure: {warm.pi} != {PI_N}"

    t0 = time.perf_counter()
    res = run_local(cfg)
    elapsed = time.perf_counter() - t0
    assert res.pi == PI_N, f"parity failure: {res.pi} != {PI_N}"

    values_per_sec = (N - 1) / elapsed
    print(
        json.dumps(
            {
                "metric": "sieve_throughput_pi_1e9_odds_pallas",
                "value": round(values_per_sec, 1),
                "unit": "values/s/chip",
                "vs_baseline": round(values_per_sec / BASELINE_VALUES_PER_SEC, 3),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
